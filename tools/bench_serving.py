#!/usr/bin/env python
"""Serving-overhead microbenchmark (CPU-runnable, wedge-proof).

Measures the HOST side of the v2 serving loop — the part PERF.md's platform
facts make load-bearing (~6-7 ms fixed relay overhead per dispatched program,
so decode throughput is dispatch-bound, not kernel-bound):

  1. allocator ops/s           — BlockedAllocator (numpy free-stack) vs the
                                 legacy list/set implementation (in-file)
  2. assembly µs/seq           — staged vectorized build_ragged_batch vs the
                                 legacy per-row-loop/fresh-array build
  3. serving loop (tiny model) — decode_chain=1 (per-token dispatch) vs
                                 decode_chain=K: host µs per decoded token
                                 (assemble + dispatch-call time off the
                                 tracer spans), programs dispatched and host
                                 syncs per token, tokens scheduled/s

No TPU required and nothing is materialized beyond a toy model — safe to run
inside any relay window or on a laptop. Results feed PERF.md's "serving
overhead" section.

Two extra modes (ISSUE 5, serving SLO observability):

  4. telemetry overhead guard   — the host-path benchmark re-runs with the
                                  tracer ENABLED; per-request lifecycle
                                  tracking + spans must cost < 5% host
                                  µs/decoded-token vs disabled
  5. --slo                      — open-loop synthetic arrival pattern
                                  (Poisson at --rate req/s) through the real
                                  engine with telemetry on: emits the
                                  TTFT/TPOT/queue-wait p50/p95/p99 +
                                  goodput table, and writes the Prometheus
                                  text exposition, the JSON metrics
                                  snapshot, and a Perfetto trace with
                                  per-request tracks + flow events

Usage: python tools/bench_serving.py [--rows 8] [--tokens 64] [--chain 8]
                                     [--slo] [--rate 40] [--requests 24]
                                     [--slo-ttft-ms 500] [--slo-tpot-ms 50]
                                     [--output serving.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Sequence

import numpy as np

# run_autotune.py idiom: `python tools/bench_serving.py` from anywhere
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# --------------------------------------------------------------------------
# Legacy (pre-fast-path) implementations, kept here so before/after can be
# re-measured from one file forever. Semantics match the old inference/ragged
# code: Python-list free list, per-row loops, fresh arrays every step.
# --------------------------------------------------------------------------
class _LegacyAllocator:
    def __init__(self, num_blocks: int):
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._free_set = set(self._free)
        self.num_blocks = num_blocks

    @property
    def free_blocks(self):
        return len(self._free)

    def allocate(self, n: int) -> List[int]:
        if n > len(self._free):
            raise RuntimeError("oom")
        out = [self._free.pop() for _ in range(n)]
        self._free_set.difference_update(out)
        return out

    def free(self, blocks: Sequence[int]) -> None:
        for b in blocks:
            if b < 0 or b >= self.num_blocks or b in self._free_set:
                raise ValueError("bad free")
            self._free.append(b)
            self._free_set.add(b)


class _LegacySeq:
    def __init__(self, uid):
        self.uid = uid
        self.seen_tokens = 0
        self.blocks: List[int] = []  # python list, as before the fast path

    def blocks_needed(self, new_tokens, block_size):
        total = self.seen_tokens + new_tokens
        return max(0, -(-total // block_size) - len(self.blocks))


class _LegacyManager:
    """Pre-fast-path StateManager: list-based descriptors + legacy allocator."""

    def __init__(self, num_blocks, block_size):
        self.allocator = _LegacyAllocator(num_blocks)
        self.block_size = block_size
        self._seqs = {}

    def extend(self, uid, new_tokens):
        seq = self._seqs.setdefault(uid, _LegacySeq(uid))
        need = seq.blocks_needed(new_tokens, self.block_size)
        if need:
            seq.blocks.extend(self.allocator.allocate(need))
        return seq


def _legacy_build(manager, uids, token_lists, max_pages, row_bucket=8, chunk_bucket=8):
    """The old build_ragged_batch: fresh arrays + per-row python fills."""
    n = len(uids)
    chunk = max(max(len(t) for t in token_lists), 1)
    chunk = ((chunk + chunk_bucket - 1) // chunk_bucket) * chunk_bucket
    rows = ((n + row_bucket - 1) // row_bucket) * row_bucket
    tokens = np.zeros((rows, chunk), np.int32)
    positions = np.zeros((rows, chunk), np.int32)
    new_lens = np.zeros((rows,), np.int32)
    block_tables = np.zeros((rows, max_pages), np.int32)
    seen = np.zeros((rows,), np.int32)
    for i, (uid, toks) in enumerate(zip(uids, token_lists)):
        toks = np.asarray(toks, np.int32)
        seq = manager.extend(uid, len(toks))
        tokens[i, : len(toks)] = toks
        positions[i, : len(toks)] = seq.seen_tokens + np.arange(len(toks))
        new_lens[i] = len(toks)
        block_tables[i, : len(seq.blocks)] = seq.blocks
        seen[i] = seq.seen_tokens
    return tokens, positions, new_lens, block_tables, seen


# --------------------------------------------------------------------------
def bench_allocator(num_blocks=8192, rounds=2000) -> Dict:
    """Alloc/free churn at the serving hot path's granularity.

    The vectorized assembly batches the whole step into ONE allocator call
    (rows × blocks-per-row), and flush frees a whole block table at once —
    so the batched shape (32 blocks/call) is what serving actually does;
    the 4-block shape shows the small-call floor. Reported as blocks/s."""
    from deepspeed_tpu.inference.ragged import BlockedAllocator

    def run(alloc_cls, per_call):
        a = alloc_cls(num_blocks)
        live = []
        t0 = time.perf_counter()
        blocks = 0
        for r in range(rounds):
            live.append(a.allocate(per_call))
            blocks += per_call
            if len(live) >= (num_blocks // per_call) // 2:
                for blk in live:
                    a.free(blk)
                    blocks += per_call
                live = []
        for blk in live:
            a.free(blk)
            blocks += per_call
        return blocks / (time.perf_counter() - t0)

    out = {}
    for label, per_call in (("batched32", 32), ("small4", 4)):
        new = run(BlockedAllocator, per_call)
        old = run(_LegacyAllocator, per_call)
        out[label] = {"new_blocks_per_sec": round(new),
                      "legacy_blocks_per_sec": round(old),
                      "speedup": round(new / old, 2)}
    return out


def bench_assembly(row_counts=(8, 32), steps=2000, prompt_len=64) -> Dict:
    """Decode-shaped assembly (1 token/row): µs per sequence-row, staged
    vectorized build vs the full legacy stack (list descriptors + legacy
    allocator + per-row loop + fresh arrays)."""
    from deepspeed_tpu.inference.ragged import BatchStaging, StateManager, build_ragged_batch

    out = {}
    for rows in row_counts:
        uids = list(range(rows))
        toks = [np.asarray([7], np.int32)] * rows

        m = StateManager(num_blocks=8192, block_size=16, max_seqs=256,
                         max_blocks_per_seq=64)
        for u in uids:
            m.extend(u, prompt_len)
            m.get(u).seen_tokens = prompt_len
        st = BatchStaging(max_pages=64)
        build_ragged_batch(m, uids, toks, 64, row_bucket=rows, staging=st)
        t0 = time.perf_counter()
        for _ in range(steps):
            build_ragged_batch(m, uids, toks, 64, row_bucket=rows, staging=st)
        staged_us = (time.perf_counter() - t0) / (steps * rows) * 1e6

        lm = _LegacyManager(8192, 16)
        for u in uids:
            lm.extend(u, prompt_len)
            lm._seqs[u].seen_tokens = prompt_len
        t0 = time.perf_counter()
        for _ in range(steps):
            _legacy_build(lm, uids, toks, 64, row_bucket=rows)
        legacy_us = (time.perf_counter() - t0) / (steps * rows) * 1e6
        out[f"rows{rows}"] = {
            "staged_us_per_seq": round(staged_us, 2),
            "legacy_us_per_seq": round(legacy_us, 2),
            "speedup": round(legacy_us / staged_us, 2)}
    return out


def _tiny_model():
    import jax

    from deepspeed_tpu.models import CausalLM, TransformerConfig

    cfg = TransformerConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128, num_layers=2,
        num_heads=4, num_kv_heads=2, max_seq_len=256)
    module = CausalLM(cfg)
    params = module.init({"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(0)},
                         {"input_ids": np.zeros((1, 8), np.int32)}, train=False)["params"]
    return cfg, params


def _kv_bench_model():
    """Capacity-sweep model: head_dim=64 (the realistic 64-128 range) so the
    int8-vs-bf16 byte ratio is the production one — per (slot, head):
    bf16 = 64*2 = 128 B, int8 = 64*1 + 4 (fp32 scale) = 68 B → 1.88x."""
    import jax

    from deepspeed_tpu.models import CausalLM, TransformerConfig

    cfg = TransformerConfig(
        vocab_size=512, hidden_size=128, intermediate_size=256, num_layers=2,
        num_heads=2, num_kv_heads=2, max_seq_len=256)
    module = CausalLM(cfg)
    params = module.init({"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(0)},
                         {"input_ids": np.zeros((1, 8), np.int32)}, train=False)["params"]
    return cfg, params


def bench_kv_capacity(kv_dtypes=("bf16", "int8", "fp8"), pool_blocks_bf16=96,
                      block_size=16, prompt_len=24, n_new=24, timing_rows=8) -> Dict:
    """The quantized-serving capacity sweep (ISSUE 10): at IDENTICAL pool
    bytes, how many concurrent requests does each KV storage dtype admit?

    The byte budget is fixed at what ``pool_blocks_bf16`` bf16 blocks cost;
    each engine derives its own block count from that budget through the real
    block-byte formula (``utils/hbm.kv_blocks_for_bytes`` — the same math the
    pre-flight guard and the allocator sizing use), then requests of
    ``prompt_len + n_new`` tokens are admitted through the REAL admission
    check until it refuses. A short real generate at ``timing_rows`` rows
    measures CPU wall µs/decoded-token per dtype (device shares the host
    here, so quantize/dequant math shows up in it — the capacity column is
    the accelerator-relevant result)."""
    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.utils.hbm import kv_slot_bytes

    cfg, params = _kv_bench_model()
    pool_bytes = pool_blocks_bf16 * block_size * kv_slot_bytes(
        cfg.num_layers, cfg.num_kv_heads, cfg.hidden_size // cfg.num_heads, 2, None)
    rng = np.random.RandomState(0)
    seq_tokens = prompt_len + n_new
    out: Dict[str, Dict] = {"pool_bytes": pool_bytes,
                            "tokens_per_request": seq_tokens, "sweep": {}}
    for kvd in kv_dtypes:
        eng = InferenceEngineV2(cfg, params, {
            "dtype": "fp32", "kv_block_size": block_size,
            "kv_pool_bytes": pool_bytes, "kv_cache_dtype": kvd,
            "max_seqs": 512, "hbm_check": "off"})
        # real admission: how many (prompt + full generation) sequences the
        # scheduler accepts concurrently at this byte budget
        admitted = 0
        while eng.can_schedule(list(range(admitted + 1)), [seq_tokens] * (admitted + 1)):
            admitted += 1
        rows = min(admitted, timing_rows)
        prompts = [rng.randint(0, cfg.vocab_size, (prompt_len,)) for _ in range(rows)]
        eng.generate(prompts, max_new_tokens=4)  # compile outside the window
        for u in list(eng.state._seqs):
            eng.flush(u)
        t0 = time.perf_counter()
        outs = eng.generate(prompts, max_new_tokens=n_new)
        wall = time.perf_counter() - t0
        total = sum(len(o) for o in outs)
        out["sweep"][kvd] = {
            "kv_bytes_per_token": eng.kv_bytes_per_token,
            "num_kv_blocks": eng.num_kv_blocks,
            "max_concurrent_requests": admitted,
            "cpu_wall_us_per_token": round(wall * 1e6 / total, 1),
        }
    if "bf16" in out["sweep"] and "int8" in out["sweep"]:
        out["int8_capacity_gain"] = round(
            out["sweep"]["int8"]["max_concurrent_requests"]
            / out["sweep"]["bf16"]["max_concurrent_requests"], 3)

    # Token-divergence step (ISSUE 17, numerics observatory): greedy-decode
    # the SAME prompts against an fp32 KV pool and each quantized pool;
    # report the first token index where a quantized pool's output departs
    # from the fp32 reference (n_new = never diverged within the horizon).
    # HIGHER is better — the number the perf gate trends per round under
    # suite "numerics" (*token_divergence_step).
    div_rows = 4
    div_rng = np.random.RandomState(17)
    div_prompts = [div_rng.randint(0, cfg.vocab_size, (prompt_len,))
                   for _ in range(div_rows)]

    def _greedy(kv_cache_dtype):
        eng = InferenceEngineV2(cfg, params, {
            "dtype": "fp32", "kv_block_size": block_size,
            "kv_pool_bytes": pool_bytes, "kv_cache_dtype": kv_cache_dtype,
            "max_seqs": 512, "hbm_check": "off"})
        return eng.generate(div_prompts, max_new_tokens=n_new)

    ref = _greedy("fp32")
    for kvd in kv_dtypes:
        got = _greedy(kvd)
        step = n_new
        for r, g in zip(ref, got):
            for i, (a, b) in enumerate(zip(r, g)):
                if int(a) != int(b):
                    step = min(step, i)
                    break
        out["sweep"].setdefault(kvd, {})["token_divergence_step"] = step
    return out


def bench_host_path(rows=8, n_new=64, chain=8, prompt_len=32) -> Dict:
    """Pure host serving overhead: the device programs are replaced by
    shape-correct host stubs, so the measured time is EXACTLY the work the
    host does per decoded token — assembly, scheduling, bookkeeping,
    dispatch-call plumbing, fetch. On a real accelerator this is the part
    that serializes with the device when every token round-trips, and the
    part the K-chain divides by K (the device side is one program either
    way; its relay cost is the ~6-7 ms/dispatch platform fact)."""
    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2

    class NullDeviceEngine(InferenceEngineV2):
        def _sample_step_fn(self, n_rows, chunk, sample_kw):
            def step(params, pool, tokens, positions, new_lens, block_tables, rng):
                return np.ones((tokens.shape[0],), np.int32), rng, pool

            return step

        def _chain_fn(self, n_rows, k, eos_id, sample_kw):
            def chain_fn(params, pool, tokens, start_pos, block_tables,
                         active, budgets, rng):
                act = np.asarray(active)
                emitted = np.where(act, np.asarray(budgets), 0).astype(np.int32)
                out = np.where(np.arange(k)[None, :] < emitted[:, None],
                               1, -1).astype(np.int32)
                return out, emitted, act & False, rng, pool

            return chain_fn

    cfg, params = _tiny_model()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, (prompt_len,)) for _ in range(rows)]

    def run(k):
        eng = NullDeviceEngine(cfg, params, {
            "dtype": "fp32", "kv_block_size": 16, "num_kv_blocks": 2048,
            "max_seqs": rows, "decode_chain": k, "hbm_check": "off"})
        eng.generate(prompts, max_new_tokens=4)  # warm staging buckets
        for u in list(eng.state._seqs):
            eng.flush(u)
        d0, s0 = eng.dispatch_count, eng.host_sync_count
        t0 = time.perf_counter()
        eng.generate(prompts, max_new_tokens=n_new)
        wall = time.perf_counter() - t0
        decoded = max(eng.tokens_decoded, 1)
        return {
            "decode_chain": k,
            "host_us_per_decode_token": round(wall * 1e6 / decoded, 2),
            "tokens_scheduled_per_sec": round((decoded + rows) / wall),
            "programs_per_decode_token": round(
                (eng.dispatch_count - d0 - 1) / decoded, 4),
            "host_syncs_per_decode_token": round(
                (eng.host_sync_count - s0 - 1) / decoded, 4),
        }

    before = run(1)
    after = run(chain)

    # --- telemetry overhead guard: same chained run with the tracer ON
    # (spans + per-request lifecycle tracking + SLO histograms). The
    # acceptance bound (ISSUE 5) is < 5% host µs/decoded-token vs the
    # committed PR-4 number (SERVING_r06.json, telemetry off); the same-run
    # enabled-vs-disabled delta is reported alongside since absolute numbers
    # drift with the machine.
    from deepspeed_tpu.telemetry import get_tracer

    R06_HOST_US = 9.38  # SERVING_r06.json host_path.chained, rows=8 k=8

    tr = get_tracer()
    was_enabled = tr.enabled
    tr.configure(enabled=True)
    try:
        with_telemetry = run(chain)
    finally:
        tr.configure(enabled=was_enabled)
        if not was_enabled:
            # leave no residue in a previously-disabled tracer; an already-
            # enabled one (bench.py under DSTPU_TELEMETRY=1) keeps its data
            tr.reset()
    overhead_pct = round(
        (with_telemetry["host_us_per_decode_token"]
         - after["host_us_per_decode_token"])
        / max(after["host_us_per_decode_token"], 1e-9) * 100, 2)

    out = {
        "rows": rows, "new_tokens": n_new,
        "per_token_loop": before, "chained": after,
        "chained_telemetry_on": with_telemetry,
        "telemetry_overhead_pct_same_run": overhead_pct,
        "host_us_speedup": round(
            before["host_us_per_decode_token"]
            / max(after["host_us_per_decode_token"], 1e-9), 2),
    }
    if rows == 8 and chain == 8:  # the committed-reference shape
        out["telemetry_vs_r06_pct"] = round(
            (with_telemetry["host_us_per_decode_token"] - R06_HOST_US)
            / R06_HOST_US * 100, 2)
    return out


def bench_end_to_end(rows=8, n_new=64, chain=8, prompt_len=32) -> Dict:
    """Tiny-model generate wall clock, decode_chain=1 vs =chain (CPU: device
    compute shares the host, so this understates the accelerator-side win —
    the host-path benchmark above is the isolation)."""
    from deepspeed_tpu.inference import InferenceEngineV2

    cfg, params = _tiny_model()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, (prompt_len,)) for _ in range(rows)]

    def run(k):
        eng = InferenceEngineV2(cfg, params, {
            "dtype": "fp32", "kv_block_size": 16, "num_kv_blocks": 512,
            "max_seqs": rows, "decode_chain": k, "hbm_check": "off"})
        eng.generate(prompts, max_new_tokens=4)  # compiles prefill + k-chain
        for u in list(eng.state._seqs):
            eng.flush(u)
        t0 = time.perf_counter()
        outs = eng.generate(prompts, max_new_tokens=n_new)
        wall = time.perf_counter() - t0
        total = sum(len(o) for o in outs)
        return {"decode_chain": k,
                "tokens_per_sec": round(total / wall, 1),
                "wall_s": round(wall, 3)}

    return {"rows": rows, "new_tokens": n_new,
            "per_token_loop": run(1), "chained": run(chain)}


def bench_slo(n_requests=24, rate=40.0, n_new=32, chain=8, prompt_len=24,
              ttft_ms=500.0, tpot_ms=50.0, seed=0, out_dir=None) -> Dict:
    """Open-loop SLO run: Poisson arrivals at ``rate`` req/s through the real
    engine with telemetry enabled. Emits the per-request percentile table
    (TTFT / TPOT / queue wait p50/p95/p99 + goodput) and writes the three
    exposition artifacts: Prometheus text, JSON snapshot, Perfetto trace
    (per-request tracks + admission->dispatch flow events)."""
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.inference import InferenceEngineV2
    from deepspeed_tpu.telemetry import get_tracer

    cfg, params = _tiny_model()
    rng = np.random.RandomState(seed)
    prompts = [rng.randint(0, cfg.vocab_size, (prompt_len,))
               for _ in range(n_requests)]
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n_requests)).tolist()

    tr = get_tracer()
    was_enabled = tr.enabled
    if was_enabled:
        # the SLO table needs a clean registry, so the resets below are
        # unavoidable — warn rather than silently eating accumulated data
        print("bench_slo: tracer already enabled; its accumulated "
              "events/metrics will be reset for the SLO measurement",
              file=sys.stderr)
    tr.configure(enabled=True)
    tr.reset()
    try:
        eng = InferenceEngineV2(cfg, params, {
            "dtype": "fp32", "kv_block_size": 16, "num_kv_blocks": 1024,
            "max_seqs": min(n_requests, 16), "decode_chain": chain,
            "hbm_check": "off",
            "serving_slo": {"ttft_ms": ttft_ms, "tpot_ms": tpot_ms}})
        # compile the prefill + chain programs outside the measured window
        eng.generate(prompts[:2], max_new_tokens=chain + 1)
        for u in list(eng.state._seqs):
            eng.flush(u)
        tr.reset()
        t0 = time.perf_counter()
        outs = eng.generate(prompts, max_new_tokens=n_new,
                            arrival_times=arrivals)
        wall = time.perf_counter() - t0

        reg = tr.registry
        table: Dict[str, Dict] = {}
        for base in ("serving/ttft_ms", "serving/tpot_ms",
                     "serving/queue_wait_ms", "serving/e2e_ms"):
            for kind, name, metric in reg.iter_metrics():
                if kind == "histogram" and name == base:
                    table[base.split("/")[1]] = {
                        "count": metric.count,
                        "p50": round(metric.quantile(0.50), 3),
                        "p95": round(metric.quantile(0.95), 3),
                        "p99": round(metric.quantile(0.99), 3),
                        "mean": round(metric.summary()["mean"], 3),
                    }
        counters = reg.counters()
        met = sum(v for k, v in counters.items() if k.startswith("serving/slo_met"))
        missed = sum(v for k, v in counters.items()
                     if k.startswith("serving/slo_missed"))
        goodput = met / max(met + missed, 1)

        out_dir = out_dir or telemetry.default_output_dir()
        prom_path = telemetry.export_prometheus(
            os.path.join(out_dir, "serving_metrics.prom"))
        snap_path = telemetry.export_json_snapshot(
            os.path.join(out_dir, "serving_metrics.json"))
        trace_path = telemetry.export_chrome_trace(
            os.path.join(out_dir, "serving_trace.json"))

        # exposition sanity: quantiles + goodput present in both formats,
        # per-request tracks + flow events present in the trace
        prom_text = open(prom_path).read()
        assert "dstpu_serving_ttft_ms_p50" in prom_text
        assert "dstpu_serving_goodput" in prom_text
        snap = json.load(open(snap_path))["metrics"]
        assert any(k.startswith("serving/ttft_ms") and "p99" in v
                   for k, v in snap.items() if isinstance(v, dict))
        doc = json.load(open(trace_path))
        n_tracks = sum(1 for e in doc["traceEvents"]
                       if e.get("ph") == "M" and e["name"] == "thread_name"
                       and str(e["args"]["name"]).startswith("req "))
        n_flows = sum(1 for e in doc["traceEvents"] if e.get("ph") in ("s", "t", "f"))
        assert n_tracks == n_requests and n_flows >= 3 * n_requests

        total_tokens = sum(len(o) for o in outs)
        return {
            "requests": n_requests, "rate_req_s": rate, "new_tokens": n_new,
            "decode_chain": chain,
            "slo": {"ttft_ms": ttft_ms, "tpot_ms": tpot_ms},
            "wall_s": round(wall, 3),
            "tokens_per_sec": round(total_tokens / wall, 1),
            "percentiles_ms": table,
            "goodput": round(goodput, 4),
            "slo_met": int(met), "slo_missed": int(missed),
            "preemptions": int(counters.get("serving/preemptions", 0)),
            "trace": {"request_tracks": n_tracks, "flow_events": n_flows},
            "artifacts": {"prometheus": prom_path, "snapshot": snap_path,
                          "perfetto": trace_path},
        }
    finally:
        tr.configure(enabled=was_enabled)
        if not was_enabled:
            tr.reset()  # leave a previously-disabled tracer empty


# --------------------------------------------------------------------------
# Serving tier (ISSUE 12): router goodput, prefix-cache savings, speculative
# accepted-tokens/forward — each leg separately benchmarkable.
# --------------------------------------------------------------------------
def bench_router(replicas=2, n_requests=48, rate=300.0, n_new=48, chain=8,
                 prompt_len=24, ttft_ms=80.0, tpot_ms=5000.0, seed=0) -> Dict:
    """Router goodput vs single engine under the same Poisson burst.

    Both sides run identical per-replica configs and the same SLO targets;
    the burst is sized so queue wait dominates TTFT on one engine (the PR-5
    ``--slo`` finding). The router's extra admission capacity (N pools, N
    schedulers, SLO-aware shedding) is what converts into goodput — on one
    CPU host the replicas still share compute, so this measures the
    scheduling win; on real accelerators each replica is its own chip and
    throughput scales too."""
    from deepspeed_tpu.inference import InferenceEngineV2, ServingRouter
    from deepspeed_tpu.inference.config import ServingSLOConfig
    from deepspeed_tpu.telemetry import get_tracer

    cfg, params = _tiny_model()
    rng = np.random.RandomState(seed)
    prompts = [rng.randint(0, cfg.vocab_size, (prompt_len,))
               for _ in range(n_requests)]
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n_requests)).tolist()
    # max_seqs=4 makes ADMISSION the bottleneck under the burst (the PR-5
    # --slo finding: queue wait eats the TTFT budget); row_bucket=4 keeps
    # each replica's programs sized to its own rows, so on this shared-CPU
    # host the router's win is admission capacity, not padded-away compute
    eng_cfg = {"dtype": "fp32", "kv_block_size": 16, "num_kv_blocks": 96,
               "max_seqs": 4, "row_bucket": 4, "decode_chain": chain,
               "hbm_check": "off",
               "serving_slo": {"ttft_ms": ttft_ms, "tpot_ms": tpot_ms}}

    tr = get_tracer()
    was_enabled = tr.enabled
    tr.configure(enabled=True)
    try:
        def goodput_of(counters):
            met = sum(v for k, v in counters.items()
                      if k.startswith("serving/slo_met"))
            missed = sum(v for k, v in counters.items()
                         if k.startswith("serving/slo_missed"))
            return met, missed, met / max(met + missed, 1)

        # ---- single engine under the burst. Warm TWICE: the second pass
        # compiles the admission-after-chain prefill variant (its pool arg
        # carries the chain output's sharding, not init's device_put) so no
        # compile lands inside the measured window.
        tr.reset()
        single = InferenceEngineV2(cfg, params, eng_cfg)
        for _ in range(2):
            single.generate(prompts[:2], max_new_tokens=chain + 1)
            for u in list(single.state._seqs):
                single.flush(u)
        tr.reset()
        t0 = time.perf_counter()
        single.generate(prompts, max_new_tokens=n_new, arrival_times=arrivals)
        single_wall = time.perf_counter() - t0
        s_met, s_missed, s_goodput = goodput_of(tr.registry.counters())

        # ---- router over N replicas, same burst
        tr.reset()
        slo = ServingSLOConfig(ttft_ms=ttft_ms, tpot_ms=tpot_ms,
                               admission="shed", admission_ttft_factor=1.2)
        router = ServingRouter.build(cfg, params, eng_cfg, replicas=replicas,
                                     slo=slo)
        for _ in range(2):  # double warmup, same reason as the single engine
            router.serve(prompts[:2 * replicas], max_new_tokens=chain + 1)
        tr.reset()
        router.reset_estimates()  # drop compile-time-poisoned latency EMAs
        router.reset_stats()
        t0 = time.perf_counter()
        outs = router.serve(prompts, max_new_tokens=n_new,
                            arrival_times=arrivals)
        router_wall = time.perf_counter() - t0
        r_met, r_missed = router.goodput()
        # shed requests count against goodput: they are arrivals the tier
        # chose not to serve (the honest denominator is every arrival)
        r_goodput = r_met / max(r_met + r_missed + router.shed_count, 1)
        served = sum(1 for o in outs if o is not None)
        return {
            "replicas": replicas, "requests": n_requests, "rate_req_s": rate,
            "new_tokens": n_new, "decode_chain": chain,
            "slo": {"ttft_ms": ttft_ms, "tpot_ms": tpot_ms},
            "single_engine": {"goodput": round(s_goodput, 4),
                              "slo_met": int(s_met), "slo_missed": int(s_missed),
                              "wall_s": round(single_wall, 3)},
            "router": {"goodput": round(r_goodput, 4),
                       "slo_met": int(r_met), "slo_missed": int(r_missed),
                       "shed": router.shed_count, "served": served,
                       "preemptions": router.preemptions,
                       "dispatches": router.stats()["dispatches"],
                       "wall_s": round(router_wall, 3)},
            "goodput_ratio": round(r_goodput / max(s_goodput, 1e-9), 3),
        }
    finally:
        tr.configure(enabled=was_enabled)
        if not was_enabled:
            tr.reset()


def bench_prefix(share=0.9, n_requests=30, sys_len=112, sfx_len=8, n_new=12,
                 chain=8, seed=0, kv_dtype="int8") -> Dict:
    """Prefix-cache prefill savings at ``--prefix-share P``: a fraction
    ``share`` of requests open with the same system prompt; the cache
    serves those tokens from the QUANTIZED pool bytes (no re-prefill, no
    re-quantization). Reports token savings + cache-hit output parity
    against a cache-off engine."""
    from deepspeed_tpu.inference import InferenceEngineV2

    cfg, params = _tiny_model()
    rng = np.random.RandomState(seed)
    sys_prompt = rng.randint(0, cfg.vocab_size, (sys_len,))
    n_shared = int(round(share * n_requests))
    prompts = []
    for i in range(n_requests):
        sfx = rng.randint(0, cfg.vocab_size, (sfx_len,))
        if i < n_shared:
            prompts.append(np.concatenate([sys_prompt, sfx]))
        else:
            prompts.append(rng.randint(0, cfg.vocab_size, (sys_len + sfx_len,)))
    rng.shuffle(prompts)
    eng_cfg = {"dtype": "fp32", "kv_block_size": 16, "num_kv_blocks": 256,
               "max_seqs": 8, "decode_chain": chain, "hbm_check": "off",
               "kv_cache_dtype": kv_dtype}

    cold = InferenceEngineV2(cfg, params, eng_cfg)
    refs = [cold.generate([p], max_new_tokens=n_new)[0] for p in prompts]

    eng = InferenceEngineV2(cfg, params, dict(eng_cfg, prefix_cache=True))
    t0 = time.perf_counter()
    outs = [eng.generate([p], max_new_tokens=n_new)[0] for p in prompts]
    wall = time.perf_counter() - t0
    identical = all((a == b).all() for a, b in zip(outs, refs))
    pc = eng.prefix_cache
    return {
        "requests": n_requests, "prefix_share": share, "kv_dtype": kv_dtype,
        "system_prompt_tokens": sys_len, "suffix_tokens": sfx_len,
        "prefill_tokens_total": eng.prefill_tokens_total,
        "prefill_tokens_cached": eng.prefill_tokens_cached,
        "prefill_savings": round(
            eng.prefill_tokens_cached / max(eng.prefill_tokens_total, 1), 4),
        "hit_rate": round(pc.hit_rate, 4),
        "cow_copies": eng.cow_copies,
        "evictions": pc.evictions,
        "cache_hit_output_identical_to_cold": bool(identical),
        "wall_s": round(wall, 3),
    }


def bench_spec(n_new=24, chain=8, n_spec=3, rows=4, seed=1) -> Dict:
    """Speculative decode on the repetitive-text corpus: accepted tokens
    per model forward (the accelerator-relevant win — each forward is one
    chain iteration either way) and per dispatch, with output parity
    against the plain chain pinned in the same run."""
    from deepspeed_tpu.inference import InferenceEngineV2

    cfg, params = _tiny_model()
    rng = np.random.RandomState(seed)
    # repetitive-text corpus: short patterns tiled (the prompt-lookup
    # proposer's home turf; greedy decode of the tiny model locks into the
    # loop, which is exactly the agreeable-text shape)
    prompts = [np.tile(rng.randint(0, cfg.vocab_size, (3 + i % 3,)), 12)[:24]
               for i in range(rows)]
    eng_cfg = {"dtype": "fp32", "kv_block_size": 16, "num_kv_blocks": 128,
               "max_seqs": rows, "decode_chain": chain, "hbm_check": "off"}

    plain = InferenceEngineV2(cfg, params, eng_cfg)
    o_plain = plain.generate(prompts, max_new_tokens=n_new)
    d_plain = plain.dispatch_count

    spec = InferenceEngineV2(cfg, params, dict(eng_cfg, spec_decode=n_spec))
    o_spec = spec.generate(prompts, max_new_tokens=n_new)
    identical = all((a == b).all() for a, b in zip(o_spec, o_plain))
    steps = max(spec.spec_model_steps, 1)
    return {
        "rows": rows, "new_tokens": n_new, "decode_chain": chain,
        "n_spec": n_spec,
        "plain_dispatches": d_plain,
        "spec_dispatches": spec.dispatch_count,
        "spec_model_forwards": spec.spec_model_steps,
        "spec_tokens_emitted": spec.spec_tokens_emitted,
        "accepted_tokens_per_forward": round(
            spec.spec_tokens_emitted / steps, 3),
        "accept_rate": round(
            (spec.spec_tokens_emitted - steps) / (steps * n_spec), 3),
        "tokens_per_dispatch_plain": round(
            sum(len(o) for o in o_plain) / max(d_plain, 1), 2),
        "tokens_per_dispatch_spec": round(
            sum(len(o) for o in o_spec) / max(spec.dispatch_count, 1), 2),
        "output_identical_to_plain": bool(identical),
    }


def _merged_quantiles(reg, name: str) -> Dict:
    """Merge every labelled child of a histogram family (one per replica)
    bucket-wise — the PR-13 federation fold — and answer percentiles over
    the combined stream."""
    from deepspeed_tpu.telemetry.registry import MetricsRegistry

    tmp = MetricsRegistry().histogram("serving/tmp_merge")
    n = 0
    for kind, base, metric in reg.iter_metrics():
        if kind == "histogram" and base == name and metric.count:
            tmp.merge_state(metric.state())
            n += 1
    if not tmp.count:
        return {"count": 0}
    return {"count": tmp.count,
            "p50": round(tmp.quantile(0.50), 3),
            "p95": round(tmp.quantile(0.95), 3),
            "p99": round(tmp.quantile(0.99), 3),
            "mean": round(tmp.summary()["mean"], 3),
            "families": n}


def bench_disagg(n_requests=24, rate=200.0, n_new=24, chain=8, prompt_len=96,
                 pool_blocks_per_replica=96, block_size=16, kv_dtype="bf16",
                 seed=0, parity_dtypes=("bf16", "int8")) -> Dict:
    """Disaggregated vs mixed serving at EQUAL hardware (ISSUE 14).

    The workload is the exact tail ROADMAP #2 names: a prefill-heavy open
    loop (long prompts, Poisson arrivals fast enough that prefills keep
    landing while decodes are in flight), where a mixed replica's long
    prefill dispatch sits between its own decode-chain boundaries and blows
    TPOT. Both rosters get the same total KV bytes and the same engine
    configs; the disagg side splits the byte budget per role
    (``utils/hbm.disagg_pool_bytes``) and migrates every finished prefill
    to the decode pool. Reported: TTFT/TPOT percentile tables per side
    (histograms merged bucket-wise across replicas), the migration-latency
    histogram, decode TPOT p99 ratio — plus greedy token parity of the
    migrated requests against a never-migrated single engine on every
    ``parity_dtypes`` pool (the acceptance pin)."""
    from deepspeed_tpu.inference import InferenceEngineV2, ServingRouter
    from deepspeed_tpu.telemetry import get_tracer
    from deepspeed_tpu.utils.hbm import kv_slot_bytes

    cfg, params = _kv_bench_model()
    rng = np.random.RandomState(seed)
    prompts = [rng.randint(0, cfg.vocab_size, (prompt_len,))
               for _ in range(n_requests)]
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n_requests)).tolist()
    # the tier budget is fixed at what 2x pool_blocks_per_replica bf16
    # blocks cost — both rosters split the SAME bytes (equal hardware)
    slot_b = kv_slot_bytes(cfg.num_layers, cfg.num_kv_heads,
                           cfg.hidden_size // cfg.num_heads, 2, None)
    total_bytes = 2 * pool_blocks_per_replica * block_size * slot_b
    eng_cfg = {"dtype": "fp32", "kv_block_size": block_size,
               "kv_cache_dtype": kv_dtype, "max_seqs": 8, "row_bucket": 4,
               "decode_chain": chain, "hbm_check": "off",
               "kv_pool_bytes": total_bytes // 2}

    tr = get_tracer()
    was_enabled = tr.enabled
    tr.configure(enabled=True)
    try:
        def run_side(roles):
            tr.reset()
            kw = {"replicas": 2, "dispatch": "threads"}
            if roles is not None:
                kw["roles"] = roles
                cfg_side = dict(eng_cfg, kv_pool_bytes=total_bytes)
            else:
                cfg_side = dict(eng_cfg)
            router = ServingRouter.build(cfg, params, cfg_side, **kw)
            for _ in range(2):  # compile both program generations off-clock
                router.serve(prompts[:4], max_new_tokens=chain + 1)
            tr.reset()
            router.reset_estimates()
            router.reset_stats()  # measured window only, not warmup
            t0 = time.perf_counter()
            outs = router.serve(prompts, max_new_tokens=n_new,
                                arrival_times=arrivals)
            wall = time.perf_counter() - t0
            reg = tr.registry
            side = {
                "wall_s": round(wall, 3),
                "served": sum(1 for o in outs if o is not None),
                "tokens_per_sec": round(
                    sum(len(o) for o in outs if o is not None) / wall, 1),
                "ttft_ms": _merged_quantiles(reg, "serving/ttft_ms"),
                "tpot_ms": _merged_quantiles(reg, "serving/tpot_ms"),
                "queue_wait_ms": _merged_quantiles(reg,
                                                   "serving/queue_wait_ms"),
                "kv_blocks": [r.engine.num_kv_blocks for r in router.replicas],
                "stats": router.stats(),
            }
            if roles is not None:
                side["migration_ms"] = _merged_quantiles(
                    reg, "serving/migration_ms")
            return side, outs

        mixed, _ = run_side(None)
        disagg, _ = run_side(["prefill", "decode"])

        # greedy parity of MIGRATED output vs a never-migrated single
        # engine, per pool storage dtype (the acceptance criterion)
        parity = {}
        par_prompts = prompts[:6]
        for pd in parity_dtypes:
            pcfg = dict(eng_cfg, kv_cache_dtype=pd,
                        kv_pool_bytes=total_bytes)
            ref = InferenceEngineV2(
                cfg, params, dict(pcfg, kv_pool_bytes=total_bytes // 2)
            ).generate(par_prompts, max_new_tokens=n_new)
            r = ServingRouter.build(cfg, params, pcfg, replicas=2,
                                    roles=["prefill", "decode"])
            outs = r.serve(par_prompts, max_new_tokens=n_new)
            parity[pd] = {
                "migrations": r.migrations,
                "token_identical": bool(all(
                    o is not None and len(o) == len(rf) and (o == rf).all()
                    for o, rf in zip(outs, ref))),
            }

        tpot_ratio = None
        if mixed["tpot_ms"].get("p99") and disagg["tpot_ms"].get("p99"):
            tpot_ratio = round(
                mixed["tpot_ms"]["p99"] / disagg["tpot_ms"]["p99"], 3)
        return {
            "requests": n_requests, "rate_req_s": rate,
            "prompt_tokens": prompt_len, "new_tokens": n_new,
            "decode_chain": chain, "kv_dtype": kv_dtype,
            "total_pool_bytes": total_bytes,
            "mixed_2_replicas": mixed,
            "disagg_1p_1d": disagg,
            "decode_tpot_p99_improvement": tpot_ratio,
            "migrated_output_parity": parity,
        }
    finally:
        tr.configure(enabled=was_enabled)
        if not was_enabled:
            tr.reset()


def disagg_smoke() -> Dict:
    """Nightly disagg smoke (ISSUE 14): a 2-pool CPU run exit-gated on
    (1) zero dropped-but-admitted requests, (2) >= 1 successful migration,
    and (3) migrated output token-identical to a never-migrated run — on a
    bf16 AND an int8 pool."""
    from deepspeed_tpu.inference import InferenceEngineV2, ServingRouter

    cfg, params = _tiny_model()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, (12 + i % 5,))
               for i in range(10)]
    out: Dict[str, Dict] = {"pools": {}}
    ok = True
    for kvd in ("bf16", "int8"):
        eng_cfg = {"dtype": "fp32", "kv_block_size": 16, "num_kv_blocks": 96,
                   "kv_cache_dtype": kvd, "max_seqs": 6, "decode_chain": 4,
                   "hbm_check": "off"}
        ref = InferenceEngineV2(cfg, params, eng_cfg).generate(
            prompts, max_new_tokens=8)
        router = ServingRouter.build(cfg, params, eng_cfg, replicas=2,
                                     roles=["prefill", "decode"])
        outs = router.serve(
            prompts, max_new_tokens=8,
            arrival_times=[0.002 * i for i in range(len(prompts))])
        finished = sum(1 for o in outs if o is not None and len(o) == 8)
        dropped = len(prompts) - finished - router.shed_count
        identical = bool(all(
            o is not None and (o == r).all() for o, r in zip(outs, ref)))
        row = {
            "requests": len(prompts), "finished": finished,
            "shed": router.shed_count,
            "dropped_after_admission": dropped,
            "migrations": router.migrations,
            "migrated_blocks": router.migrated_blocks,
            "migration_failures": router.migration_failures,
            "output_identical_to_never_migrated": identical,
        }
        row_ok = (dropped == 0 and router.migrations >= 1 and identical)
        row["pass"] = bool(row_ok)
        ok = ok and row_ok
        out["pools"][kvd] = row
    out["pass"] = bool(ok)
    return out


def router_smoke(replicas=2) -> Dict:
    """Nightly serving-router smoke: N CPU replicas under a shared-prefix
    burst. Exit-gates (run_nightly.sh): prefix_hit_rate > 0 and ZERO
    dropped-but-admitted requests — every arrival either finished or was
    shed BEFORE admission, never lost after."""
    from deepspeed_tpu.inference import ServingRouter
    from deepspeed_tpu.inference.config import ServingSLOConfig

    cfg, params = _tiny_model()
    rng = np.random.RandomState(0)
    sys_prompt = rng.randint(0, cfg.vocab_size, (48,))
    prompts = [np.concatenate([sys_prompt, rng.randint(0, cfg.vocab_size, (4,))])
               for _ in range(12)]
    eng_cfg = {"dtype": "fp32", "kv_block_size": 16, "num_kv_blocks": 64,
               "max_seqs": 4, "decode_chain": 4, "hbm_check": "off",
               "prefix_cache": True}
    slo = ServingSLOConfig(ttft_ms=60_000.0, admission="shed")
    router = ServingRouter.build(cfg, params, eng_cfg, replicas=replicas,
                                 slo=slo)
    # two waves so the second wave's admissions hit the first wave's blocks
    outs = router.serve(prompts[:replicas], max_new_tokens=8)
    outs += router.serve(prompts[replicas:],
                         max_new_tokens=8,
                         arrival_times=[0.002 * i for i in
                                        range(len(prompts) - replicas)])
    finished = sum(1 for o in outs if o is not None and len(o) == 8)
    hit_rate = max(r.engine.prefix_cache.hit_rate for r in router.replicas)
    cached = sum(r.engine.prefill_tokens_cached for r in router.replicas)
    dropped_after_admission = len(prompts) - finished - router.shed_count
    out = {
        "replicas": replicas, "requests": len(prompts),
        "finished": finished, "shed": router.shed_count,
        "dropped_after_admission": dropped_after_admission,
        "prefix_hit_rate": round(hit_rate, 4),
        "prefill_tokens_cached": cached,
        "dispatches": router.stats()["dispatches"],
        "pass": bool(hit_rate > 0 and dropped_after_admission == 0
                     and finished + router.shed_count == len(prompts)),
    }
    return out


def bench_remote(n_rtt=40, n_new=24, chain=8) -> Dict:
    """Cross-process serving-fabric bench (ISSUE 18): replica DAEMONS in
    other OS processes behind the unchanged router, measuring the three
    costs the fabric adds over a local replica — per-dispatch RPC RTT,
    wire KV migration (quantized bytes verbatim), and a mid-burst drain
    handoff. Rows land under perf-ledger suite ``fabric``."""
    import statistics
    import tempfile
    import threading

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from fabric_smoke import _engine_cfg, _prompts, shutdown_daemon, spawn_daemon

    import jax

    from deepspeed_tpu.fabric.remote import RemoteReplica, _get
    from deepspeed_tpu.fabric.wire import export_to_wire
    from deepspeed_tpu.inference.router import ServingRouter

    out_dir = tempfile.mkdtemp(prefix="bench_remote_")
    run_id = f"bench-remote-{os.getpid():x}"
    da = spawn_daemon(1, run_id, _engine_cfg(), out_dir)
    db = spawn_daemon(2, run_id, _engine_cfg(), out_dir)
    ra = rb = None
    try:
        ra = RemoteReplica(da.url, start_heartbeat=False)
        rb = RemoteReplica(db.url, start_heartbeat=False)
        # --- dispatch RTT: the fixed per-hop tax every remote dispatch pays
        rtts = []
        for _ in range(n_rtt):
            t0 = time.perf_counter()
            _get(da.url, "/healthz", timeout=5.0)
            rtts.append((time.perf_counter() - t0) * 1e3)
        rtts.sort()
        # --- wire migration: export a live request on A, import on B
        prompt = _prompts(n=1)[0]
        suffix = ra.try_admit(21, prompt, [], [])
        rng = jax.random.PRNGKey(0)
        toks, rng = ra._put_sample([21], [suffix.tolist()], rng,
                                   (("do_sample", False),))
        ra.decode_chain([21], [int(np.asarray(toks).ravel()[0])],
                        [n_new], chain, rng)
        t0 = time.perf_counter()
        export = ra.export_request(21)
        imported = rb.import_request(22, export)
        wire_ms = (time.perf_counter() - t0) * 1e3
        wire_bytes = len(json.dumps(export_to_wire(export)))
        ra.flush(21)
        rb.flush(22)
        # --- drain handoff: quiesce daemon A mid-burst; its in-flight
        # requests migrate to B over the same wire plane
        router = ServingRouter([ra, rb])
        box: Dict = {}

        def run():
            box["outs"] = router.serve(_prompts(), max_new_tokens=48)

        t = threading.Thread(target=run)
        t.start()
        deadline = time.time() + 120.0
        while time.time() < deadline and not router.replicas[0].active:
            time.sleep(0.002)
        t_drain = time.perf_counter()
        router.request_drain(0)
        while time.time() < deadline and (router.replicas[0].active
                                          or router.replicas[0].migrating):
            time.sleep(0.002)
        drain_ms = (time.perf_counter() - t_drain) * 1e3
        t.join(600.0)
        outs = box.get("outs") or []
        return {
            "replicas": 2, "transport": "http/json",
            "dispatch_rtt_ms": {
                "p50": round(statistics.median(rtts), 3),
                "p95": round(rtts[int(0.95 * (len(rtts) - 1))], 3),
                "n": n_rtt,
            },
            "wire_migration_ms": round(wire_ms, 3),
            "wire_kv_bytes": wire_bytes,
            "wire_import_ok": bool(imported),
            "drain_handoff_ms": round(drain_ms, 3),
            "drain_handoffs": router.stats()["migrations"],
            "completed": sum(1 for o in outs if o is not None),
            "requests": len(outs),
        }
    finally:
        for r in (ra, rb):
            if r is not None:
                r.close()
        shutdown_daemon(da)
        shutdown_daemon(db)


def _emit_perf_ledger(payload: dict, suite: str = "serving") -> None:
    """Append this run's numeric tree to the unified perf ledger, suite
    ``serving`` (ISSUE 16) — the SAME flattener migration uses on the
    legacy SERVING_rNN artifacts, so a number emitted today and one
    migrated from r12 are directly comparable rows. The fabric bench
    (``--remote``) lands under suite ``fabric`` instead. Best-effort: the
    bench must never fail because the ledger dir is unwritable."""
    try:
        import time as _time

        from deepspeed_tpu.telemetry.fleet import get_identity
        from deepspeed_tpu.telemetry.perfledger import (
            PerfLedger, default_backend, default_round, resolve_git_sha,
        )
        from deepspeed_tpu.telemetry.perfmigrate import rows_from_tree

        rows = rows_from_tree(
            suite, payload, round=default_round(),
            backend=default_backend(), run_id=get_identity().run_id,
            git_sha=resolve_git_sha(), time_unix=_time.time())
        # Token-divergence steps additionally land under suite "numerics"
        # (ISSUE 17): the numerics headline patterns
        # (perfgate.HEADLINE_PATTERNS["numerics"]) gate that suite, not
        # "serving", and the number is an accuracy trajectory, not a speed.
        from deepspeed_tpu.telemetry.perfledger import make_row

        sweep = (payload.get("kv_capacity") or {}).get("sweep") or {}
        for kvd, cols in sweep.items():
            if "token_divergence_step" in cols:
                rows.append(make_row(
                    "numerics", f"{kvd}/token_divergence_step",
                    float(cols["token_divergence_step"]), "steps",
                    direction="higher", method="probe", samples=1,
                    round=default_round(), backend=default_backend(),
                    run_id=get_identity().run_id,
                    git_sha=resolve_git_sha(), time_unix=_time.time()))
        PerfLedger().append(rows)
    except Exception as e:  # noqa: BLE001 — evidence plane, not the bench
        print(f"[bench_serving] perf-ledger append skipped: {e}",
              file=sys.stderr)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=8)
    ap.add_argument("--tokens", type=int, default=64)
    ap.add_argument("--chain", type=int, default=8)
    ap.add_argument("--kv-dtype", type=str, default="bf16,int8,fp8",
                    help="comma list of KV-cache storage dtypes for the "
                         "fixed-byte capacity sweep (bf16|int8|fp8)")
    ap.add_argument("--slo", action="store_true",
                    help="run the open-loop SLO mode (TTFT/TPOT/queue-wait "
                         "percentiles + goodput + exposition artifacts)")
    ap.add_argument("--rate", type=float, default=40.0,
                    help="--slo arrival rate, requests/s (Poisson)")
    ap.add_argument("--requests", type=int, default=24,
                    help="--slo number of synthetic requests")
    ap.add_argument("--slo-ttft-ms", type=float, default=500.0)
    ap.add_argument("--slo-tpot-ms", type=float, default=50.0)
    ap.add_argument("--replicas", type=int, default=0,
                    help="run the serving-router goodput bench over N "
                         "engine replicas (vs a single engine, same burst)")
    ap.add_argument("--prefix-share", type=float, default=None,
                    help="run the prefix-cache bench with this fraction of "
                         "requests sharing a system prompt")
    ap.add_argument("--spec", action="store_true",
                    help="run the speculative-decode bench on the "
                         "repetitive-text corpus")
    ap.add_argument("--router-smoke", action="store_true",
                    help="nightly smoke: 2 CPU replicas + shared-prefix "
                         "burst; exits nonzero unless prefix_hit_rate > 0 "
                         "and zero dropped-but-admitted requests")
    ap.add_argument("--disagg", action="store_true",
                    help="run the disaggregated-vs-mixed bench: 1 prefill + "
                         "1 decode replica vs 2 mixed at equal hardware "
                         "under a prefill-heavy Poisson burst (TTFT/TPOT "
                         "percentiles + migration histogram + parity)")
    ap.add_argument("--disagg-smoke", action="store_true",
                    help="nightly smoke: 2-pool disagg CPU run; exits "
                         "nonzero unless zero dropped-but-admitted, >=1 "
                         "migration, and migrated output token-identical "
                         "to a never-migrated run on bf16 AND int8 pools")
    ap.add_argument("--remote", action="store_true",
                    help="run the cross-process fabric bench: replica "
                         "daemons in separate OS processes (dispatch RTT, "
                         "wire KV migration, drain handoff; perf-ledger "
                         "suite 'fabric')")
    ap.add_argument("--output", type=str, default=None)
    args = ap.parse_args()

    if args.remote:
        res = {"remote": bench_remote(chain=args.chain)}
        text = json.dumps(res, indent=2)
        print(text)
        if args.output:
            with open(args.output, "w") as f:
                f.write(text + "\n")
        _emit_perf_ledger(res, suite="fabric")
        sys.exit(0)

    if args.disagg_smoke:
        res = disagg_smoke()
        print(json.dumps(res, indent=2))
        if args.output:
            with open(args.output, "w") as f:
                json.dump(res, f, indent=2)
        sys.exit(0 if res["pass"] else 1)

    if args.disagg:
        res = {"disagg": bench_disagg(chain=args.chain)}
        text = json.dumps(res, indent=2)
        print(text)
        if args.output:
            with open(args.output, "w") as f:
                f.write(text + "\n")
        _emit_perf_ledger(res)
        sys.exit(0)

    if args.router_smoke:
        res = router_smoke(replicas=max(args.replicas, 2))
        print(json.dumps(res, indent=2))
        if args.output:
            with open(args.output, "w") as f:
                json.dump(res, f, indent=2)
        sys.exit(0 if res["pass"] else 1)

    out = {
        "allocator": bench_allocator(),
        "assembly": bench_assembly(row_counts=(args.rows, 4 * args.rows)),
        "host_path": bench_host_path(rows=args.rows, n_new=args.tokens,
                                     chain=args.chain),
        "end_to_end": bench_end_to_end(rows=args.rows, n_new=args.tokens,
                                       chain=args.chain),
        "kv_capacity": bench_kv_capacity(
            kv_dtypes=tuple(d.strip() for d in args.kv_dtype.split(",") if d.strip())),
    }
    if args.slo:
        out["slo"] = bench_slo(n_requests=args.requests, rate=args.rate,
                               n_new=args.tokens, chain=args.chain,
                               ttft_ms=args.slo_ttft_ms,
                               tpot_ms=args.slo_tpot_ms)
    if args.replicas:
        # the router bench owns its burst shape (an overload the single
        # engine cannot serve within budget — that is what the goodput
        # comparison measures); only the replica count and chain ride the CLI
        out["router"] = bench_router(replicas=args.replicas, chain=args.chain)
    if args.prefix_share is not None:
        out["prefix_cache"] = bench_prefix(share=args.prefix_share,
                                           chain=args.chain)
    if args.spec:
        out["spec_decode"] = bench_spec(chain=args.chain)
    text = json.dumps(out, indent=2)
    print(text)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text + "\n")
    _emit_perf_ledger(out)


if __name__ == "__main__":
    main()
