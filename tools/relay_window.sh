#!/bin/bash
# Full on-chip measurement queue, fired automatically by relay_probe_loop.sh
# the first time a probe reports UP (round 5: relay windows can be minutes
# long, so zero human latency between recovery and measurement).
#
# Every stage is timeout-guarded; a mid-stage wedge costs that stage only.
# Artifacts: BENCH_r05_builder.json, ATTN_SWEEP_r05.txt, AUTOTUNE.json,
# all progress to .relay_window.log.
cd /root/repo || exit 1
LOG=/root/repo/.relay_window.log
SWEEP=/root/repo/ATTN_SWEEP_r05.txt
stamp() { date -u +%H:%M:%S; }

echo "=== relay window open $(stamp) ===" >> "$LOG"

# 1. The hardened bench: headline + extras, each in its own guarded child.
timeout 3600 python bench.py > /root/repo/BENCH_r05_builder.json 2>> "$LOG"
echo "bench exit $? at $(stamp)" >> "$LOG"

# 2. Flash-attention block/k_splits sweep (fwd + grad, two sequence lengths).
{
  echo "== sweep fwd B=4 S=1024 $(stamp)"
  timeout 900 python tools/profile_bench.py --stage attn-sweep --batch 4 --seq 1024
  echo "== sweep fwd B=1 S=4096 $(stamp)"
  timeout 900 python tools/profile_bench.py --stage attn-sweep --batch 1 --seq 4096
  echo "== sweep grad B=4 S=1024 $(stamp)"
  timeout 1200 python tools/profile_bench.py --stage attn-sweep --grad --batch 4 --seq 1024
  echo "== sweep grad B=1 S=4096 $(stamp)"
  timeout 1200 python tools/profile_bench.py --stage attn-sweep --grad --batch 1 --seq 4096
} >> "$SWEEP" 2>&1
echo "sweep done at $(stamp)" >> "$LOG"

# 3. Autotuner artifact on hardware (bench.py consumes it when committed).
timeout 2700 python tools/run_autotune.py >> "$LOG" 2>&1
echo "autotune exit $? at $(stamp)" >> "$LOG"

echo "=== relay window queue done $(stamp) ===" >> "$LOG"
touch /root/repo/.relay_window_done
