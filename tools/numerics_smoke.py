#!/usr/bin/env python
"""Numerics observatory smoke: detection + quiet, exit-gated BOTH ways.

The nightly's proof that ISSUE 17's sentinel actually fires and actually
stays quiet (``tools/run_nightly.sh`` commits ``NUMERICS_rNN.log``):

  1. **Clean run MUST be quiet** — a 20-step train run with the sentinel
     sampling every step raises ZERO divergence events and ZERO wire-drift
     events. A sentinel that cries wolf gets ignored; a noisy round fails
     the stage.
  2. **Injected corruption MUST be detected within one sampled step** —
     ``diagnostics.faultinject.FaultInjector.flip_param_bit`` flips one
     mantissa bit in ONE dp replica's copy of one replicated fp32 param
     (the classic silent-data-corruption fault), and the next sampled
     train step must latch a divergence event. No detection => exit 1
     (the inverted gate: green is evidence of a working sentinel, not a
     silent one).
  3. **Wire probes MUST cover every lossy codec** — each codec in
     ``numerics.LOSSY_CODECS`` is routed through the grad-mean facade at
     trace time, then one forced probe round must return a relative error
     for each, inside its pinned ``WIRE_REL_ERR_BOUNDS`` envelope.
  4. **Abort policy MUST raise** — with ``divergence_policy="abort"`` the
     same injected flip must surface as ``TrainingHealthError``.

Accuracy trajectories land in the perf ledger (``--ledger``), suite
``numerics``: ``wire_rel_err/<codec>`` (direction=lower) and
``divergence_detect_steps`` (direction=lower) — gated by the PR-16
median+MAD machinery exactly like latency (see perfgate.HEADLINE_PATTERNS).

Prints one JSON line of evidence (the committed-log artifact).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

CLEAN_STEPS = 20


def _engine(policy: str = "log", sentinel_every: int = 1):
    import deepspeed_tpu

    eng, *_ = deepspeed_tpu.initialize(
        model=_model_spec(),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
            "steps_per_print": 10_000,
            "numerics": {
                "enabled": True,
                "sample_every": 4,
                "sentinel_sample_every": sentinel_every,
                "divergence_policy": policy,
            },
        },
    )
    return eng


def _model_spec():
    sys.path.insert(0, os.path.join(REPO, "tests"))
    from unit.simple_model import simple_model_spec

    return simple_model_spec()


def _batch(eng, seed):
    from unit.simple_model import random_batch

    return random_batch(eng.train_batch_size, seed=seed)


def run_smoke() -> dict:
    import jax

    from deepspeed_tpu.collectives import selector
    from deepspeed_tpu.diagnostics.faultinject import FaultInjector
    from deepspeed_tpu.diagnostics.manager import TrainingHealthError
    from deepspeed_tpu.telemetry import numerics

    evidence: dict = {"clean": {}, "inject": {}, "wire": {}, "abort": {}}
    gates: dict = {}

    # ---- gate 1: clean 20-step run stays quiet -------------------------
    eng = _engine()
    for s in range(CLEAN_STEPS):
        eng.train_batch(batch=_batch(eng, seed=s))
    obs = numerics.get_observatory()
    evidence["clean"] = {
        "steps": CLEAN_STEPS,
        "divergence_events": obs.divergence_events_seen,
        "wire_drift_events": obs.wire_drift_events,
        "checked": int(jax.device_get(eng.state.numerics.checked)),
    }
    gates["clean_quiet"] = (obs.divergence_events_seen == 0
                            and obs.wire_drift_events == 0
                            and evidence["clean"]["checked"] == CLEAN_STEPS)

    # ---- gate 2: injected bit flip detected within one sampled step ----
    leaf = FaultInjector().flip_param_bit(eng)
    before = obs.divergence_events_seen
    detect_steps = -1
    for extra in range(1, 4):
        eng.train_batch(batch=_batch(eng, seed=100 + extra))
        if obs.divergence_events_seen > before:
            detect_steps = extra
            break
    evidence["inject"] = {"leaf": leaf, "detect_steps": detect_steps,
                          "sentinel_sample_every": 1}
    gates["inject_detected_within_one_sampled_step"] = detect_steps == 1

    # ---- gate 3: wire probes cover every lossy codec -------------------
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from deepspeed_tpu.runtime.engine import _facade_grad_mean
    from deepspeed_tpu.utils.compat import shard_map

    mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))
    x = jnp.ones((8, 512), jnp.float32)
    for codec in sorted(numerics.LOSSY_CODECS):
        selector.configure(facade_algorithm="ring", facade_codec=codec,
                           codecs=(codec,))

        def make():
            def f(g):
                return _facade_grad_mean(g, "dp")

            return shard_map(f, mesh=mesh, in_specs=P("dp"),
                             out_specs=P("dp"), check_vma=False)

        jax.make_jaxpr(make())(x)  # trace-time route registration
    selector.configure()
    rels = obs.sample_now()
    covered = {k.split("/", 1)[1] for k in rels}
    in_bounds = {
        c: (rels.get(f"all_reduce/{c}") is not None
            and 0.0 < rels[f"all_reduce/{c}"] < numerics.WIRE_REL_ERR_BOUNDS[c])
        for c in sorted(numerics.LOSSY_CODECS)}
    evidence["wire"] = {"rel_err": rels, "covered": sorted(covered)}
    gates["wire_covers_every_lossy_codec"] = (
        covered >= set(numerics.LOSSY_CODECS) and all(in_bounds.values()))

    # ---- gate 4: abort policy raises ----------------------------------
    eng2 = _engine(policy="abort")
    eng2.train_batch(batch=_batch(eng2, seed=0))
    FaultInjector().flip_param_bit(eng2)
    raised = False
    try:
        eng2.train_batch(batch=_batch(eng2, seed=1))
    except TrainingHealthError as e:
        raised = True
        evidence["abort"] = {"raised": True, "step": e.step,
                             "dump": bool(e.dump_path)}
    gates["abort_policy_raises"] = raised

    evidence["gates"] = gates
    evidence["pass"] = all(gates.values())
    return evidence


def emit_ledger(evidence: dict) -> int:
    """Append the accuracy trajectories to the unified perf ledger (suite
    ``numerics``). Best-effort like bench_serving: the smoke verdict never
    depends on the ledger dir being writable."""
    try:
        from deepspeed_tpu.telemetry.fleet import get_identity
        from deepspeed_tpu.telemetry.perfledger import (
            PerfLedger, default_backend, default_round, make_row,
            resolve_git_sha,
        )

        common = dict(backend=default_backend(), round=default_round(),
                      run_id=get_identity().run_id,
                      git_sha=resolve_git_sha(), time_unix=time.time())
        rows = [make_row("numerics", "divergence_detect_steps",
                         float(evidence["inject"]["detect_steps"]), "steps",
                         direction="lower", method="probe", samples=1,
                         **common)]
        for key, rel in evidence["wire"]["rel_err"].items():
            codec = key.split("/", 1)[1]
            rows.append(make_row("numerics", f"wire_rel_err/{codec}",
                                 float(rel), "rel", direction="lower",
                                 method="probe", samples=1, **common))
        return PerfLedger().append(rows)
    except Exception as e:  # noqa: BLE001 — evidence plane, not the gate
        print(f"[numerics_smoke] perf-ledger append skipped: {e}",
              file=sys.stderr)
        return 0


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ledger", action="store_true",
                    help="append accuracy rows to the unified perf ledger")
    args = ap.parse_args()
    evidence = run_smoke()
    if args.ledger:
        evidence["ledger_rows"] = emit_ledger(evidence)
    print(json.dumps(evidence, sort_keys=True))
    sys.exit(0 if evidence["pass"] else 1)


if __name__ == "__main__":
    main()
