"""Run the autotuner over the bench headline config and write AUTOTUNE.json.

Reference analog: ``autotuning/autotuner.py:404 tune()`` producing the
experiment table + chosen config (round-3 verdict item 9: a committed
artifact of the tuner choosing a config on real hardware). On the TPU this
reproduces PERF.md's scan/fused-CE table automatically; ``bench.py`` consumes
the artifact (model-level knobs for the headline run) when present.

Usage:  python tools/run_autotune.py [--steps N] [--out AUTOTUNE.json]
        [--cpu-smoke]   (tiny model on CPU — validates the plumbing only)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--out", default=os.path.join(REPO, "AUTOTUNE.json"))
    ap.add_argument("--cpu-smoke", action="store_true",
                    help="tiny model on CPU (plumbing check, not a perf artifact)")
    args = ap.parse_args()

    if args.cpu_smoke:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    if args.cpu_smoke:
        # Also drops the axon factory: with it registered, the first
        # computation can block on a wedged relay even when pinned to CPU.
        from deepspeed_tpu.utils.cpu_backend import force_cpu_backend

        force_cpu_backend()
    import numpy as np

    from deepspeed_tpu.autotuning import Autotuner
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    on_tpu = jax.default_backend() == "tpu"
    if args.cpu_smoke:
        dims = dict(vocab_size=256, hidden_size=32, intermediate_size=64,
                    num_layers=2, num_heads=4, max_seq_len=64)
        model_kw = dict(dims)
        seq, micros, stages, gas = 32, (1,), (1,), 1
    else:
        # the bench.py headline config's dimensions — IMPORTED so the tuner
        # and the bench cannot drift; recorded in the artifact and rejected
        # by bench._autotune_overrides on mismatch
        from bench import GPT2_HEADLINE_DIMS

        dims = dict(GPT2_HEADLINE_DIMS)
        model_kw = dict(dims, dtype=jax.numpy.bfloat16)
        seq, micros, stages, gas = 1024, (4, 8), (1,), 8

    def factory(**overrides):
        return causal_lm_spec(TransformerConfig(**model_kw, **overrides),
                              example_seq_len=seq)

    def batch_fn(s):
        rng = np.random.default_rng(s)
        # a POOL with rows for the largest candidate; the tuner slices each
        # candidate's train_batch_size rows out of it
        n_dev = len(jax.devices())
        return {"input_ids": rng.integers(
            0, dims["vocab_size"], (max(micros) * gas * n_dev, seq), dtype=np.int32)}

    # match the CONSUMER's step shape (bench.py headline: gas + clipping) —
    # a micro that wins at gas=1 need not win at gas=8
    base = {"optimizer": {"type": "AdamW", "params": {"lr": 1e-4, "weight_decay": 0.1}},
            "gradient_accumulation_steps": gas,
            "gradient_clipping": 1.0,
            "bf16": {"enabled": not args.cpu_smoke},
            "steps_per_print": 100000}
    tuner = Autotuner(
        factory(), base,
        micro_batch_candidates=micros,
        stage_candidates=stages,
        remat_candidates=(False,),
        model_factory=factory,
        # the PERF.md round-3 table's model-level knobs, plus round-5
        # flash-kernel scheduling candidates (attn_kwargs flows through
        # TransformerConfig -> causal_attention -> pallas kernel; dropped on
        # the XLA path) so the tuner can pick kernel blocking on hardware
        model_override_candidates=(
            {}, {"scan_layers": False},
            {"scan_layers": False, "fused_ce": False},
            {"scan_layers": False, "fused_ce": False,
             "attn_kwargs": {"block_q": 512, "block_k": 512, "k_splits": 2}},
            {"scan_layers": False, "fused_ce": False,
             "attn_kwargs": {"block_q": 1024, "block_k": 1024, "k_splits": 4}},
        ) if not args.cpu_smoke else ({}, {"scan_layers": False},
                                      {"scan_layers": False, "fused_ce": False}),
    )
    best, results = tuner.tune(steps=args.steps, batch_fn=batch_fn)

    artifact = {
        "backend": jax.default_backend(),
        "plumbing_smoke_only": bool(args.cpu_smoke),
        "model_dims": dims,
        "best_config": best,
        "best_model_overrides": tuner.best_overrides or {},
        "table": [
            {"config": {k: v for k, v in r.config.items()},
             "throughput_samples_per_s": round(r.throughput, 2),
             "error": r.error}
            for r in results
        ],
    }
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"wrote {args.out}: best micro="
          f"{best['train_micro_batch_size_per_gpu']} overrides={tuner.best_overrides}")


if __name__ == "__main__":
    main()
