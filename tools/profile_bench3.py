"""Third-stage: decompose the step — honest fwd+bwd, optimizer-only, device
matmul rate inside one program, bigger micro-batch scaling."""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models import TransformerConfig, causal_lm_spec, CausalLM
from deepspeed_tpu.topology.mesh import set_mesh


def fetch_time(fn, out_leaf, n=5, warmup=2):
    for _ in range(warmup):
        r = fn()
    _ = np.asarray(out_leaf(r))
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn()
    _ = np.asarray(out_leaf(r))
    return (time.perf_counter() - t0) / n


def main():
    cfg = TransformerConfig(
        vocab_size=50304, hidden_size=768, intermediate_size=3072,
        num_layers=12, num_heads=12, max_seq_len=1024,
        norm="layernorm", activation="gelu", position="learned",
        tie_embeddings=True, dtype=jnp.bfloat16,
    )
    seq = 1024
    module = CausalLM(cfg)
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=seq),
        config={
            "train_micro_batch_size_per_gpu": 8,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 1},
            "bf16": {"enabled": True},
            "steps_per_print": 10_000,
        },
    )
    set_mesh(engine.mesh)
    state = engine.state
    params16 = jax.jit(lambda p: jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16) if jnp.issubdtype(x.dtype, jnp.floating) else x, p))(state.params)

    rng = np.random.default_rng(0)

    # 0. true device matmul rate: 50 matmuls inside one program
    a = jnp.zeros((8192, 8192), jnp.bfloat16)

    @jax.jit
    def mm50(a):
        def body(i, acc):
            return acc + a @ a * (1.0 / (i + 1))
        return jax.lax.fori_loop(0, 50, body, jnp.zeros_like(a))[0, 0]

    t = fetch_time(lambda: mm50(a), lambda r: r, n=2, warmup=1)
    print(f"50x 8k matmul in-program: {t*1e3:.1f} ms => {50*2*8192**3/t/1e12:.1f} TFLOP/s")

    for micro in (8, 32):
        b = {"input_ids": jnp.asarray(rng.integers(0, cfg.vocab_size, (micro, seq), dtype=np.int32))}

        @jax.jit
        def fwd(p, b):
            loss, _ = module.apply({"params": p}, b, train=False)
            return loss

        @jax.jit
        def fwdbwd(p, b):
            def loss_fn(pp):
                loss, _ = module.apply({"params": pp}, b, train=False)
                return loss
            l, g = jax.value_and_grad(loss_fn)(p)
            return l, g

        t_f = fetch_time(lambda: fwd(params16, b), lambda r: r)
        t_fb = fetch_time(lambda: fwdbwd(params16, b), lambda r: r[1]["lm_head"]["embedding"] if "lm_head" in r[1] else jax.tree_util.tree_leaves(r[1])[0])
        fwd_fl = 2 * 124e6 * micro * seq  # 2*N*T matmul flops approx (fwd)
        print(f"micro={micro}: fwd={t_f*1e3:.1f}ms ({fwd_fl/t_f/1e12:.1f} TF/s) "
              f"fwd+bwd={t_fb*1e3:.1f}ms ({3*fwd_fl/t_fb/1e12:.1f} TF/s)")

    # optimizer-only update (adamw on fp32 master)
    tx = engine.tx
    grads = jax.tree_util.tree_map(lambda x: jnp.ones(x.shape, jnp.float32), state.params)

    @jax.jit
    def opt_only(params, opt_state, grads):
        updates, new_opt = tx.update(grads, opt_state, params)
        import optax
        return optax.apply_updates(params, updates), new_opt

    t_o = fetch_time(lambda: opt_only(state.params, state.opt_state, grads),
                     lambda r: jax.tree_util.tree_leaves(r[0])[0])
    print(f"optimizer-only: {t_o*1e3:.1f} ms")

    # embedding + lm-head matmul microbenches (vocab is the big matmul)
    emb = jnp.zeros((50304, 768), jnp.bfloat16)
    h = jnp.zeros((8 * 1024, 768), jnp.bfloat16)

    @jax.jit
    def head(h, emb):
        return (h @ emb.T)[0, 0]

    t_h = fetch_time(lambda: head(h, emb), lambda r: r)
    print(f"lm head matmul (8k x 768 x 50k): {t_h*1e3:.2f} ms => {2*8192*768*50304/t_h/1e12:.1f} TF/s")


if __name__ == "__main__":
    main()
