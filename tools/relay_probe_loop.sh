#!/bin/bash
# Probe the axon TPU relay every 10 min; append one line per probe to
# .relay_probe.log. On the FIRST successful probe, fire the full on-chip
# measurement queue (tools/relay_window.sh) exactly once — relay windows
# have been minutes long, so the queue must start with zero human latency.
# Stop by: touch /root/repo/.relay_probe_stop
LOG=/root/repo/.relay_probe.log
while [ ! -f /root/repo/.relay_probe_stop ]; do
  T=$(date -u +%H:%M:%S)
  if timeout 120 python -c "import jax; x=jax.numpy.ones((128,128)); print(float((x@x).sum()))" >/dev/null 2>&1; then
    echo "$T UP" >> "$LOG"
    if [ ! -f /root/repo/.relay_window_done ] && [ ! -f /root/repo/.relay_window_running ]; then
      touch /root/repo/.relay_window_running
      /root/repo/tools/relay_window.sh
      rm -f /root/repo/.relay_window_running
    fi
  else
    echo "$T down" >> "$LOG"
  fi
  sleep 600
done
