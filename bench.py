"""Benchmark: flagship CausalLM training + inference throughput.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extras": {...}}

Headline (value/vs_baseline): tokens/sec/chip for GPT-2-small (125M params,
bf16, seq 1024, gas 4) full train steps (fwd+bwd+AdamW) through the engine on
the single real TPU chip. vs_baseline = achieved MFU / 0.45, the north-star
MFU from BASELINE.md (the reference's Ulysses/FPDT blogs claim ~54%/55% peak
on A100).

"extras" adds the other BASELINE.json tracked configs that fit one chip
(round-2 verdict items 3/9): a Llama-style ZeRO-3 + remat + fused-CE config
(largest that fits 16G HBM), a Mixtral-style expert-parallel step, and the v2
inference engine's p50 TTFT + decode tokens/sec. Each extra is best-effort —
a failure records the error string instead of killing the headline number.

Falls back to a tiny model on CPU so the bench always completes.

NOTE: sync via explicit scalar fetch (np.asarray) — jax.block_until_ready is
a no-op on the axon TPU relay (see PERF.md).
"""

from __future__ import annotations

import json
import time


def _train_tokens_per_sec(engine, batch, steps, warmup):
    import numpy as np

    for _ in range(warmup):
        m = engine.train_batch(batch)
    np.asarray(m["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        m = engine.train_batch(batch)
    np.asarray(m["loss"])
    dt = time.perf_counter() - t0
    return engine.train_batch_size * batch["input_ids"].shape[1] * steps / dt


def bench_train_gpt2(on_tpu, peak_flops):
    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    if on_tpu:
        # scan_layers=False: the per-layer scan's activation stacking costs
        # ~25% of wall-clock at this depth (PERF.md round 3); fused_ce=False:
        # the chunked-vocab CE is a memory lever, not a speed lever — the XLA
        # logits path is faster whenever the fp32 logits fit.
        cfg = TransformerConfig(
            vocab_size=50304, hidden_size=768, intermediate_size=3072,
            num_layers=12, num_heads=12, max_seq_len=1024,
            norm="layernorm", activation="gelu", position="learned",
            tie_embeddings=True, dtype=jax.numpy.bfloat16,
            scan_layers=False, fused_ce=False,
        )
        micro, seq, steps, warmup, gas = 4, 1024, 10, 3, 8
    else:
        cfg = TransformerConfig(
            vocab_size=512, hidden_size=128, intermediate_size=256,
            num_layers=2, num_heads=4, max_seq_len=256,
        )
        micro, seq, steps, warmup, gas = 2, 128, 3, 1, 1

    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=seq),
        config={
            "train_micro_batch_size_per_gpu": micro,
            "gradient_accumulation_steps": gas,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4, "weight_decay": 0.1}},
            "zero_optimization": {"stage": 1},
            "bf16": {"enabled": True},
            "gradient_clipping": 1.0,
            "steps_per_print": 10_000,
        },
    )
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (engine.train_batch_size, seq), dtype=np.int32)}
    tok_per_sec = _train_tokens_per_sec(engine, batch, steps, warmup)
    mfu = tok_per_sec * cfg.flops_per_token(seq) / peak_flops
    return tok_per_sec, mfu, seq


def bench_train_llama_z3(peak_flops):
    """Largest-fitting Llama-style config: ZeRO-3 placement + remat.

    Single chip, so ZeRO-3 is placement-only (fsdp=1) — this measures the
    dense-model step the Llama-3-8B multi-chip config is built from. Sizing:
    ~550M params keeps master+Adam fp32 states (12 bytes/param) + grads +
    bf16 compute + remat activations + fp32 logits ([4,2048,32000] = 1 GiB;
    the XLA CE path is faster than the chunked fused CE whenever the logits
    fit — PERF.md round 3) inside 16G HBM."""
    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    cfg = TransformerConfig(
        vocab_size=32000, hidden_size=1536, intermediate_size=6144,
        num_layers=14, num_heads=16, num_kv_heads=8, head_dim=96,
        max_seq_len=2048, norm="rmsnorm", activation="silu_glu", position="rope",
        remat=True, dtype=jax.numpy.bfloat16, scan_layers=False, fused_ce=False,
    )
    seq = 2048
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=seq),
        config={
            "train_micro_batch_size_per_gpu": 4,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 3},
            "bf16": {"enabled": True},
            "gradient_clipping": 1.0,
            "steps_per_print": 10_000,
        },
    )
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (engine.train_batch_size, seq), dtype=np.int32)}
    tok_per_sec = _train_tokens_per_sec(engine, batch, steps=5, warmup=2)
    return {
        "tokens_per_sec_per_chip": round(tok_per_sec, 1),
        "mfu": round(tok_per_sec * cfg.flops_per_token(seq) / peak_flops, 4),
        "params_m": round(cfg.num_params() / 1e6),
    }


def bench_train_moe(peak_flops):
    """Mixtral-style expert-parallel step (8 experts, top-2) on one chip."""
    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    cfg = TransformerConfig(
        vocab_size=32000, hidden_size=1024, intermediate_size=2816,
        num_layers=8, num_heads=16, num_kv_heads=8, max_seq_len=1024,
        norm="rmsnorm", activation="silu_glu", position="rope",
        num_experts=8, moe_top_k=2, remat=True, dtype=jax.numpy.bfloat16,
        scan_layers=False, fused_ce=False,
    )
    seq = 1024
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=seq),
        config={
            "train_micro_batch_size_per_gpu": 8,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 1},
            "bf16": {"enabled": True},
            "steps_per_print": 10_000,
        },
    )
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (engine.train_batch_size, seq), dtype=np.int32)}
    tok_per_sec = _train_tokens_per_sec(engine, batch, steps=5, warmup=2)
    return {
        "tokens_per_sec_per_chip": round(tok_per_sec, 1),
        # flops_per_token uses ACTIVE params (top-2 of 8 experts) for MoE
        "mfu_active": round(tok_per_sec * cfg.flops_per_token(seq) / peak_flops, 4),
        "total_params_m": round(cfg.num_params() / 1e6),
    }


def bench_inference():
    """v1 engine generate: p50 TTFT (prefill) + steady decode tok/s."""
    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig

    cfg = TransformerConfig(
        vocab_size=50304, hidden_size=768, intermediate_size=3072,
        num_layers=12, num_heads=12, max_seq_len=2048,
        norm="layernorm", activation="gelu", position="learned",
        tie_embeddings=True, dtype=jax.numpy.bfloat16,
    )
    from deepspeed_tpu.models import CausalLM

    module = CausalLM(cfg)
    example = {"input_ids": jax.numpy.zeros((1, 8), jax.numpy.int32)}
    params = module.init({"params": jax.random.PRNGKey(0)}, example, train=False)["params"]
    engine = deepspeed_tpu.init_inference(
        cfg, params=params,
        config={"dtype": "bfloat16", "seq_bucket": 256, "max_out_tokens": 256},
    )
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, (1, 200), dtype=np.int32)

    # warm BOTH compiled programs (the generate cache keys on max_new_tokens)
    n_new = 128
    engine.generate(prompt, max_new_tokens=1, do_sample=False)
    engine.generate(prompt, max_new_tokens=n_new, do_sample=False)

    # TTFT proxy: 1-new-token generate (prefill + 1 decode), p50 of 7
    ttfts = []
    for _ in range(7):
        t0 = time.perf_counter()
        engine.generate(prompt, max_new_tokens=1, do_sample=False)
        ttfts.append(time.perf_counter() - t0)
    p50_ttft = sorted(ttfts)[len(ttfts) // 2]

    # decode throughput: long generation minus the TTFT part
    t0 = time.perf_counter()
    engine.generate(prompt, max_new_tokens=n_new, do_sample=False)
    dt = time.perf_counter() - t0
    decode_tok_s = (n_new - 1) / max(dt - p50_ttft, 1e-6)
    return {"p50_ttft_ms": round(p50_ttft * 1e3, 2),
            "decode_tokens_per_sec": round(decode_tok_s, 1)}


def bench_train_long_context(peak_flops):
    """Long-sequence training on one chip: seq 8k, flash kernel + remat.

    The BASELINE-tracked long-context config (8B @ 32k Ulysses) needs a pod;
    this measures the single-chip building block it is made of — the causal
    flash kernel's triangle grid at long S, where attention grows to ~half
    the model flops."""
    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    seq = 8192
    cfg = TransformerConfig(
        vocab_size=32000, hidden_size=768, intermediate_size=3072,
        num_layers=12, num_heads=12, max_seq_len=seq,
        norm="rmsnorm", activation="silu_glu", position="rope",
        remat=True, dtype=jax.numpy.bfloat16, scan_layers=False, fused_ce=False,
    )
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=seq),
        config={
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 4,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 1},
            "bf16": {"enabled": True},
            "steps_per_print": 10_000,
        },
    )
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (engine.train_batch_size, seq), dtype=np.int32)}
    tok_per_sec = _train_tokens_per_sec(engine, batch, steps=5, warmup=2)
    return {
        "seq_len": seq,
        "tokens_per_sec_per_chip": round(tok_per_sec, 1),
        "mfu": round(tok_per_sec * cfg.flops_per_token(seq) / peak_flops, 4),
    }


def _probe_tpu(timeout_s: float = 180.0) -> bool:
    """True iff the TPU backend initializes within timeout_s.

    A wedged relay (stale lease after a killed process) makes jax.devices()
    hang for MINUTES with no exception — probing in a subprocess keeps this
    process clean so it can fall back to the CPU smoke bench instead of
    hanging forever. Must run BEFORE jax is imported in this process."""
    import os
    import signal
    import subprocess
    import sys

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        return False  # explicitly CPU-pinned (tests): nothing to probe
    # DEVNULL + new session: a wedged child's TPU-runtime grandchildren must
    # not inherit pipes we would block draining, and the timeout kill must
    # take the whole process group down.
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "import jax, sys; sys.exit(0 if jax.default_backend() == 'tpu' else 1)"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        start_new_session=True,
    )
    try:
        return proc.wait(timeout=timeout_s) == 0
    except subprocess.TimeoutExpired:
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except OSError:
            pass
        return False


def main() -> None:
    import os

    if not _probe_tpu():
        # Fall back hard to CPU so the bench always emits its JSON line.
        # sitecustomize may have imported jax already (latching JAX_PLATFORMS
        # at import), so set the env var, drop the experimental backend
        # factory, AND update the live config.
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            from jax._src import xla_bridge

            xla_bridge._backend_factories.pop("axon", None)
        except Exception:  # noqa: BLE001 - jax internals moved; env var may suffice
            pass
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    peak_flops = 197e12 if on_tpu else 1e12  # v5e bf16 peak per chip

    tok_per_sec, mfu, seq = bench_train_gpt2(on_tpu, peak_flops)

    extras = {}
    if on_tpu:
        for name, fn in (
            ("llama_550m_zero3_remat", lambda: bench_train_llama_z3(peak_flops)),
            ("mixtral_style_moe", lambda: bench_train_moe(peak_flops)),
            ("long_context_8k", lambda: bench_train_long_context(peak_flops)),
            ("inference_v1_gpt2_125m", bench_inference),
        ):
            try:
                extras[name] = fn()
            except Exception as e:  # best-effort: record, don't kill the headline
                extras[name] = {"error": f"{type(e).__name__}: {e}"[:300]}

    result = {
        "metric": f"tokens_per_sec_per_chip_gpt2_125m_bf16_seq{seq}" if on_tpu
        else f"tokens_per_sec_cpu_smoke_seq{seq}",
        "value": round(tok_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.45, 4),
        **({"extras": extras} if extras else {}),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
