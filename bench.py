"""Benchmark: flagship CausalLM training + inference throughput.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extras": {...}}

Headline (value/vs_baseline): tokens/sec/chip for GPT-2-small (125M params,
bf16, seq 1024, gas 4) full train steps (fwd+bwd+AdamW) through the engine on
the single real TPU chip. vs_baseline = achieved MFU / 0.45, the north-star
MFU from BASELINE.md (the reference's Ulysses/FPDT blogs claim ~54%/55% peak
on A100).

"extras" adds the other BASELINE.json tracked configs that fit one chip
(round-2 verdict items 3/9): a Llama-style ZeRO-3 + remat + fused-CE config
(largest that fits 16G HBM), a Mixtral-style expert-parallel step, and the v2
inference engine's p50 TTFT + decode tokens/sec. Each extra is best-effort —
a failure records the error string instead of killing the headline number.

Falls back to a tiny model on CPU so the bench always completes.

NOTE: sync via explicit scalar fetch (np.asarray) — jax.block_until_ready is
a no-op on the axon TPU relay (see PERF.md).
"""

from __future__ import annotations

import json
import time


def _train_tokens_per_sec(engine, batch, steps, warmup):
    import numpy as np

    for _ in range(warmup):
        m = engine.train_batch(batch)
    np.asarray(m["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        m = engine.train_batch(batch)
    np.asarray(m["loss"])
    dt = time.perf_counter() - t0
    return engine.train_batch_size * batch["input_ids"].shape[1] * steps / dt


# The headline model's dimensions — shared with tools/run_autotune.py so the
# tuner and the bench cannot drift (an AUTOTUNE.json recorded for different
# dims is rejected).
PEAK_FLOPS_TPU = 197e12  # v5e bf16 peak per chip
PEAK_FLOPS_CPU_SMOKE = 1e12  # nominal denominator for the degraded smoke

GPT2_HEADLINE_DIMS = dict(
    vocab_size=50304, hidden_size=768, intermediate_size=3072,
    num_layers=12, num_heads=12, max_seq_len=1024,
    norm="layernorm", activation="gelu", position="learned",
    tie_embeddings=True,
)


def _telemetry_enabled() -> bool:
    """Telemetry opt-in for bench runs (DSTPU_TELEMETRY=1). Default OFF so
    the headline timed loop carries zero instrumentation overhead. The
    truthy-spelling parse lives in ONE place: telemetry.env_enabled."""
    from deepspeed_tpu import telemetry

    return telemetry.env_enabled()


def _telemetry_section(engine, batch, steps=5):
    """5-step instrumented run + trace export.

    The phase breakdown comes from the telemetry registry — the SAME numbers
    the engine's spans recorded, not a second ad-hoc timing pass (single
    source of truth). The loop uses the reference-style
    forward/backward/step API so the trace holds real fwd/bwd/step spans
    (train_batch's fused program has no separable phases); a tiny facade
    all_reduce probe guarantees at least one comm collective span with
    payload-bytes metadata even on a single-chip mesh."""
    import os

    import jax
    import numpy as np

    from deepspeed_tpu import telemetry

    tr = telemetry.get_tracer()
    tr.configure(enabled=True)
    # drop spans/counters from the timed headline loop: the section's
    # breakdown must describe exactly this 5-step run (the fused-dispatch
    # 'step' spans recorded by train_batch would otherwise blend with the
    # optimizer-only parity 'step' spans below into a meaningless mix)
    tr.reset()

    # comm probe: one facade collective over all local devices (ds_bench's
    # smallest sibling) — records op/axis/dtype/bytes/world tags at trace time
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    import deepspeed_tpu.comm as dist

    # one resolution of the moved/renamed shard_map API for the whole tree
    from deepspeed_tpu.utils.compat import shard_map
    devs = np.array(jax.devices())
    mesh = Mesh(devs, ("dp",))
    probe = shard_map(lambda v: dist.all_reduce(v, "dp"),
                      mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    np.asarray(jax.jit(probe)(jnp.ones((len(devs), 256), jnp.float32)))
    # algorithmic sibling: one hop-composed quantized all-reduce so the trace
    # also holds per-hop coll:* spans + the algorithm/codec routing tags
    # (collectives/ subsystem; harmless single tiny collective)
    probe2 = shard_map(
        lambda v: dist.all_reduce(v[0], "dp", algorithm="ring2d", codec="int8",
                                  block_size=128)[None],
        mesh=mesh, in_specs=P("dp"), out_specs=P("dp"), check_vma=False)
    np.asarray(jax.jit(probe2)(jnp.ones((len(devs), 256), jnp.float32)))

    gas = engine.config.gradient_accumulation_steps
    micro = {k: np.asarray(v)[: max(1, np.asarray(v).shape[0] // gas)]
             for k, v in batch.items()}
    for _ in range(steps):
        engine.forward(micro)            # "fwd" span (eval forward)
        for _ in range(gas):
            engine.backward(batch=micro)  # "bwd" span (fwd+bwd grad program)
        engine.step()                     # "step" span (optimizer update)
    engine.flush_monitor()

    out_dir = telemetry.default_output_dir()
    trace_path = telemetry.export_chrome_trace(os.path.join(out_dir, "bench_trace.json"))
    jsonl_path = telemetry.export_jsonl(os.path.join(out_dir, "bench_events.jsonl"))
    comm = {k: v for k, v in tr.registry.counters().items() if k.startswith("comm/")}
    return {
        "phases": tr.phase_summary(),
        "comm": comm,
        "memory": tr.sample_memory(),
        "trace": trace_path,
        "events": jsonl_path,
    }


def _autotune_overrides():
    """Model-level knobs from a committed AUTOTUNE.json (tools/run_autotune.py
    on real hardware — round-3 verdict item 9). Falls back to the PERF.md
    round-3 hand-measured values when absent, CPU-smoke-only, or recorded for
    different model dims. Never raises (the bench must always complete)."""
    import os

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "AUTOTUNE.json")
    try:
        with open(path) as f:
            art = json.load(f)
        if (isinstance(art, dict) and art.get("backend") == "tpu"
                and not art.get("plumbing_smoke_only")
                and art.get("model_dims", GPT2_HEADLINE_DIMS) == GPT2_HEADLINE_DIMS):
            ov = dict(art.get("best_model_overrides") or {})
            micro = art.get("best_config", {}).get("train_micro_batch_size_per_gpu")
            return ov, micro
    except (OSError, ValueError, TypeError, AttributeError):
        pass
    return None, None


def bench_train_gpt2(on_tpu, peak_flops):
    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    if on_tpu:
        # scan_layers=False: the per-layer scan's activation stacking costs
        # ~25% of wall-clock at this depth (PERF.md round 3); fused_ce=False:
        # the chunked-vocab CE is a memory lever, not a speed lever — the XLA
        # logits path is faster whenever the fp32 logits fit. A committed
        # AUTOTUNE.json (tuner-chosen on hardware) overrides both.
        overrides, tuned_micro = _autotune_overrides()
        autotuned = overrides is not None
        if overrides is None:
            overrides = {"scan_layers": False, "fused_ce": False}
        cfg = TransformerConfig(
            **GPT2_HEADLINE_DIMS, dtype=jax.numpy.bfloat16, **overrides,
        )
        micro, seq, steps, warmup, gas = (tuned_micro or 4), 1024, 10, 3, 8
    else:
        cfg = TransformerConfig(
            vocab_size=512, hidden_size=128, intermediate_size=256,
            num_layers=2, num_heads=4, max_seq_len=256,
        )
        micro, seq, steps, warmup, gas = 2, 128, 3, 1, 1

    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=seq),
        config={
            "train_micro_batch_size_per_gpu": micro,
            "gradient_accumulation_steps": gas,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4, "weight_decay": 0.1}},
            "zero_optimization": {"stage": 1},
            "hbm_guard": {"enabled": True},
            "bf16": {"enabled": True},
            "gradient_clipping": 1.0,
            "steps_per_print": 10_000,
            # opt-in (DSTPU_TELEMETRY=1): span tracing through the engine's
            # config block; disabled (default) the hooks are attribute checks
            **({"telemetry": {"enabled": True}} if _telemetry_enabled() else {}),
            # flight recorder + recompile/step-time watch: a wedged or crashed
            # bench run leaves telemetry_out/flight_record.jsonl behind (dump
            # on unhandled exception / SIGTERM). health probes stay OFF so
            # the headline timed loop compiles the identical step program.
            "diagnostics": {"enabled": True, "health": {"enabled": False}},
        },
    )
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (engine.train_batch_size, seq), dtype=np.int32)}
    tok_per_sec = _train_tokens_per_sec(engine, batch, steps, warmup)
    mfu = tok_per_sec * cfg.flops_per_token(seq) / peak_flops
    telem = _telemetry_section(engine, batch) if _telemetry_enabled() else None
    # provenance: a tuned micro changes the workload shape — stamp it so
    # trend tooling never attributes the delta to a code change
    stamp = ({"overrides": overrides, "micro": micro} if on_tpu and autotuned else None)
    return tok_per_sec, mfu, seq, stamp, telem


def bench_train_llama_z3(peak_flops):
    """Largest-fitting Llama-style config: ZeRO-3 placement + remat.

    Single chip, so ZeRO-3 is placement-only (fsdp=1) — this measures the
    dense-model step the Llama-3-8B multi-chip config is built from. Sizing:
    ~550M params keeps master+Adam fp32 states (12 bytes/param) + grads +
    bf16 compute + remat activations + fp32 logits ([4,2048,32000] = 1 GiB;
    the XLA CE path is faster than the chunked fused CE whenever the logits
    fit — PERF.md round 3) inside 16G HBM."""
    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    cfg = TransformerConfig(
        vocab_size=32000, hidden_size=1536, intermediate_size=6144,
        num_layers=14, num_heads=16, num_kv_heads=8, head_dim=96,
        max_seq_len=2048, norm="rmsnorm", activation="silu_glu", position="rope",
        remat=True, dtype=jax.numpy.bfloat16, scan_layers=False, fused_ce=False,
    )
    seq = 2048
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=seq),
        config={
            "train_micro_batch_size_per_gpu": 4,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 3},
            "hbm_guard": {"enabled": True},
            "bf16": {"enabled": True},
            "gradient_clipping": 1.0,
            "steps_per_print": 10_000,
        },
    )
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (engine.train_batch_size, seq), dtype=np.int32)}
    tok_per_sec = _train_tokens_per_sec(engine, batch, steps=5, warmup=2)
    return {
        "tokens_per_sec_per_chip": round(tok_per_sec, 1),
        "mfu": round(tok_per_sec * cfg.flops_per_token(seq) / peak_flops, 4),
        "params_m": round(cfg.num_params() / 1e6),
    }


def bench_train_moe(peak_flops):
    """Mixtral-style expert-parallel step (8 experts, top-2) on one chip."""
    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    cfg = TransformerConfig(
        vocab_size=32000, hidden_size=1024, intermediate_size=2816,
        num_layers=8, num_heads=16, num_kv_heads=8, max_seq_len=1024,
        norm="rmsnorm", activation="silu_glu", position="rope",
        num_experts=8, moe_top_k=2, remat=True, dtype=jax.numpy.bfloat16,
        scan_layers=False, fused_ce=False,
    )
    seq = 1024
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=seq),
        config={
            "train_micro_batch_size_per_gpu": 8,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 1},
            "hbm_guard": {"enabled": True},
            "bf16": {"enabled": True},
            "steps_per_print": 10_000,
        },
    )
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (engine.train_batch_size, seq), dtype=np.int32)}
    tok_per_sec = _train_tokens_per_sec(engine, batch, steps=5, warmup=2)
    return {
        "tokens_per_sec_per_chip": round(tok_per_sec, 1),
        # flops_per_token uses ACTIVE params (top-2 of 8 experts) for MoE
        "mfu_active": round(tok_per_sec * cfg.flops_per_token(seq) / peak_flops, 4),
        "total_params_m": round(cfg.num_params() / 1e6),
    }


def _bench_train_dense(peak_flops, *, hidden, inter, layers, heads, kv_heads,
                       seq, micro, zero, steps=4, warmup=2, bf16_accum=False):
    """Shared harness for the >=1B dense configs (round-3 verdict item 2).

    bf16_accum: carry the grad accumulator in bf16 — for the offload configs
    this HALVES the D2H gradient transfer, the dominant offload cost (the
    reference's CPU optimizer likewise receives 16-bit gradients)."""
    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    cfg = TransformerConfig(
        vocab_size=32000, hidden_size=hidden, intermediate_size=inter,
        num_layers=layers, num_heads=heads, num_kv_heads=kv_heads,
        max_seq_len=seq, norm="rmsnorm", activation="silu_glu", position="rope",
        remat=True, dtype=jax.numpy.bfloat16, scan_layers=False, fused_ce=True,
    )
    bf16_section = {"enabled": True}
    if bf16_accum:
        bf16_section["accumulate_grads_in_fp32"] = False
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=seq),
        config={
            "train_micro_batch_size_per_gpu": micro,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": zero or {"stage": 3},
            "hbm_guard": {"enabled": True},
            "bf16": bf16_section,
            "gradient_clipping": 1.0,
            "steps_per_print": 10_000,
            # post-mortem artifact for the big/novel configs (these are the
            # runs that have wedged the relay before; see EXTRA_BENCHES)
            "diagnostics": {"enabled": True, "health": {"enabled": False}},
        },
    )
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (engine.train_batch_size, seq), dtype=np.int32)}
    tok_per_sec = _train_tokens_per_sec(engine, batch, steps=steps, warmup=warmup)
    return {
        "tokens_per_sec_per_chip": round(tok_per_sec, 1),
        "mfu": round(tok_per_sec * cfg.flops_per_token(seq) / peak_flops, 4),
        "params_m": round(cfg.num_params() / 1e6),
    }


def bench_train_dense_1b(peak_flops):
    """Largest dense model whose FULL fp32 Adam state fits the 16G chip.

    Round 5 on-chip finding: the original 12-layer (~890M) sizing put
    ~14.2 GiB of optimizer/weight state on a 16 GiB chip and WEDGED the relay
    during param materialization (no OOM exception — the init RPC never
    returned; see PERF.md round 5). 10 layers (~760M) leaves ~3.8 GiB of
    headroom for remat activations + fused-CE chunks."""
    return _bench_train_dense(
        peak_flops, hidden=2048, inter=8192, layers=10, heads=16, kv_heads=8,
        seq=2048, micro=1, zero={"stage": 3})


def bench_train_dense_2b_offload(peak_flops):
    """~2B params: does NOT fit on-chip with Adam states (~31 GiB), DOES fit
    with ZeRO-Offload — bf16 weights+grads (~7.8 GiB) on chip, fp32 master +
    moments on host, optimizer update as a compiled CPU program (the
    DeepSpeedCPUAdam analog; reference swap_tensor/partitioned_optimizer_swapper.py:29).
    First on-chip evidence for the offload path (round-3 verdict weak item 2)."""
    return _bench_train_dense(
        peak_flops, hidden=2560, inter=10240, layers=18, heads=20, kv_heads=10,
        seq=2048, micro=1, steps=3, warmup=1, bf16_accum=True,
        zero={"stage": 3, "offload_optimizer": {"device": "cpu"}})


def bench_train_dense_2b_twinflow(peak_flops):
    """Twin-Flow partial offload (reference ZeRO-Offload++,
    blogs/deepspeed-offloadpp claims 3x/6x over full offload): same ~2B model
    as ``dense_2b_offload_host`` but with ratio=0.75 — the hottest 25% of
    master bytes update on-chip in a fused program and skip the host
    round-trip. HBM math: bf16 w+g ~7.8 GiB + 0.5B on-chip fp32 states
    ~6 GiB + remat activations.

    bf16_accum stays False here: the Twin-Flow stats/partition programs
    require fp32 gradient accumulation (the engine warns and keeps fp32 if
    asked otherwise), so unlike ``dense_2b_offload_host`` the D2H gradient
    transfer is NOT halved — Twin-Flow's win is moving less state, not
    thinner gradients."""
    return _bench_train_dense(
        peak_flops, hidden=2560, inter=10240, layers=18, heads=20, kv_heads=10,
        seq=2048, micro=1, steps=3, warmup=1, bf16_accum=False,
        zero={"stage": 3, "offload_optimizer": {"device": "cpu", "ratio": 0.75}})


def _nvme_swap_dir():
    """A directory on REAL storage for the swap bench.

    tempfile.mkdtemp() lands on /tmp, which is tmpfs on many hosts — swapping
    there measures RAM, not NVMe. Honor an explicit override, else probe
    candidates and take the first that is not memory-backed; report the fs
    type alongside the numbers either way so a RAM-backed run is visible."""
    import os
    import tempfile

    def fstype(path):
        try:
            import subprocess

            out = subprocess.run(["stat", "-f", "-c", "%T", path],
                                 capture_output=True, text=True, timeout=10)
            return out.stdout.strip() or "unknown"
        except Exception:
            return "unknown"

    override = os.environ.get("DSTPU_BENCH_NVME_DIR")
    if override:
        os.makedirs(override, exist_ok=True)
        return tempfile.mkdtemp(prefix="dstpu_bench_nvme_", dir=override), fstype(override)
    for cand in (tempfile.gettempdir(), os.path.dirname(os.path.abspath(__file__))):
        t = fstype(cand)
        if t not in ("tmpfs", "ramfs"):
            return tempfile.mkdtemp(prefix="dstpu_bench_nvme_", dir=cand), t
    d = tempfile.mkdtemp(prefix="dstpu_bench_nvme_")
    return d, fstype(d)


def bench_train_nvme_offload(peak_flops):
    """ZeRO-Infinity step: optimizer moments swapped to NVMe between steps
    through the AIO pool, plus the raw disk bandwidth the swapper rides on
    (comparable against the reference's 10/5 GB/s DeepNVMe claim).

    Model dims are deliberately IDENTICAL to ``llama_550m_zero3_remat`` so the
    extras pair reads as on-chip-optimizer vs NVMe-swapped-optimizer overhead
    for the same network."""
    import shutil

    folder, fs = _nvme_swap_dir()
    try:
        out = _bench_train_dense(
            peak_flops, hidden=1536, inter=6144, layers=14, heads=16, kv_heads=8,
            seq=2048, micro=1, steps=3, warmup=1,
            zero={"stage": 3,
                  "offload_optimizer": {"device": "nvme", "nvme_path": folder}},
            bf16_accum=True)
        from deepspeed_tpu.nvme.perf import run_io_benchmark

        io = run_io_benchmark(folder, size_mb=256, num_threads=4)
        out["disk_write_gbps"] = round(io["write_gbps"], 2)
        out["disk_read_gbps"] = round(io["read_gbps"], 2)
        out["swap_dir_fstype"] = fs
        return out
    finally:
        shutil.rmtree(folder, ignore_errors=True)


def bench_inference():
    """v1 engine generate: p50 TTFT (prefill) + steady decode tok/s."""
    import numpy as np

    import deepspeed_tpu

    cfg, params = _gpt2_inference_model()
    engine = deepspeed_tpu.init_inference(
        cfg, params=params,
        config={"dtype": "bfloat16", "seq_bucket": 256, "max_out_tokens": 256},
    )
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, (1, 200), dtype=np.int32)

    # warm BOTH compiled programs (the generate cache keys on max_new_tokens)
    n_new = 128
    engine.generate(prompt, max_new_tokens=1, do_sample=False)
    engine.generate(prompt, max_new_tokens=n_new, do_sample=False)

    # TTFT proxy: 1-new-token generate (prefill + 1 decode), p50 of 7
    ttfts = []
    for _ in range(7):
        t0 = time.perf_counter()
        engine.generate(prompt, max_new_tokens=1, do_sample=False)
        ttfts.append(time.perf_counter() - t0)
    p50_ttft = sorted(ttfts)[len(ttfts) // 2]

    # decode throughput: long generation minus the TTFT part
    t0 = time.perf_counter()
    engine.generate(prompt, max_new_tokens=n_new, do_sample=False)
    dt = time.perf_counter() - t0
    decode_tok_s = (n_new - 1) / max(dt - p50_ttft, 1e-6)
    return {"p50_ttft_ms": round(p50_ttft * 1e3, 2),
            "decode_tokens_per_sec": round(decode_tok_s, 1)}


def _gpt2_inference_model():
    import jax

    from deepspeed_tpu.models import CausalLM, TransformerConfig

    cfg = TransformerConfig(
        vocab_size=50304, hidden_size=768, intermediate_size=3072,
        num_layers=12, num_heads=12, max_seq_len=2048,
        norm="layernorm", activation="gelu", position="learned",
        tie_embeddings=True, dtype=jax.numpy.bfloat16,
    )
    module = CausalLM(cfg)
    example = {"input_ids": jax.numpy.zeros((1, 8), jax.numpy.int32)}
    params = module.init({"params": jax.random.PRNGKey(0)}, example,
                         train=False)["params"]
    return cfg, params


def bench_inference_llama():
    """Llama-family TTFT/decode evidence (BASELINE tracks the reference's
    llama serving numbers; same 550M geometry as the training extra so the
    pair reads together)."""
    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import CausalLM, TransformerConfig

    cfg = TransformerConfig(
        vocab_size=32000, hidden_size=1536, intermediate_size=6144,
        num_layers=14, num_heads=16, num_kv_heads=8, head_dim=96,
        max_seq_len=2048, norm="rmsnorm", activation="silu_glu",
        position="rope", dtype=jax.numpy.bfloat16,
    )
    module = CausalLM(cfg)
    example = {"input_ids": jax.numpy.zeros((1, 8), jax.numpy.int32)}
    params = module.init({"params": jax.random.PRNGKey(0)}, example,
                         train=False)["params"]
    engine = deepspeed_tpu.init_inference(
        cfg, params=params,
        config={"dtype": "bfloat16", "seq_bucket": 256, "max_out_tokens": 256},
    )
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, (1, 200), dtype=np.int32)
    n_new = 128
    engine.generate(prompt, max_new_tokens=1, do_sample=False)
    engine.generate(prompt, max_new_tokens=n_new, do_sample=False)
    ttfts = []
    for _ in range(7):
        t0 = time.perf_counter()
        engine.generate(prompt, max_new_tokens=1, do_sample=False)
        ttfts.append(time.perf_counter() - t0)
    p50_ttft = sorted(ttfts)[len(ttfts) // 2]
    t0 = time.perf_counter()
    engine.generate(prompt, max_new_tokens=n_new, do_sample=False)
    dt = time.perf_counter() - t0
    return {"params_m": round(cfg.num_params() / 1e6),
            "p50_ttft_ms": round(p50_ttft * 1e3, 2),
            "decode_tokens_per_sec": round((n_new - 1) / max(dt - p50_ttft, 1e-6), 1)}


def bench_inference_v2():
    """FastGen-analog serving evidence (reference claims its ragged/paged v2
    engine, not v1, for the TTFT/throughput headlines): continuous batching
    through the paged KV pool — single-sequence p50 TTFT + aggregate decode
    tokens/sec with 8 concurrent 200-token prompts."""
    import numpy as np

    from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2

    cfg, params = _gpt2_inference_model()
    # hbm_check="refuse": an oversized pool/params refuses BEFORE placement
    # instead of wedging the relay mid-materialization
    eng = InferenceEngineV2(cfg, params, {"dtype": "bf16", "hbm_check": "refuse"})
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, (200,), dtype=np.int32)
               for _ in range(8)]

    # warm every bucketed program this workload touches
    eng.generate(prompts[:1], max_new_tokens=1)
    eng.generate(prompts, max_new_tokens=8)

    ttfts = []
    for _ in range(5):
        t0 = time.perf_counter()
        eng.generate(prompts[:1], max_new_tokens=1)
        ttfts.append(time.perf_counter() - t0)
    p50_ttft = sorted(ttfts)[len(ttfts) // 2]

    n_new = 64
    t0 = time.perf_counter()
    eng.generate(prompts, max_new_tokens=n_new)
    dt = time.perf_counter() - t0
    # aggregate decode rate net of the (measured) prefill phase
    decode_tok_s = 8 * (n_new - 1) / max(dt - p50_ttft, 1e-6)
    return {"p50_ttft_ms": round(p50_ttft * 1e3, 2),
            "batch8_decode_tokens_per_sec": round(decode_tok_s, 1)}


def bench_train_long_context(peak_flops):
    """Long-sequence training on one chip: seq 8k, flash kernel + remat.

    The BASELINE-tracked long-context config (8B @ 32k Ulysses) needs a pod;
    this measures the single-chip building block it is made of — the causal
    flash kernel's triangle grid at long S, where attention grows to ~half
    the model flops."""
    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    seq = 8192
    cfg = TransformerConfig(
        vocab_size=32000, hidden_size=768, intermediate_size=3072,
        num_layers=12, num_heads=12, max_seq_len=seq,
        norm="rmsnorm", activation="silu_glu", position="rope",
        remat=True, dtype=jax.numpy.bfloat16, scan_layers=False, fused_ce=False,
    )
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=seq),
        config={
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 4,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 1},
            "hbm_guard": {"enabled": True},
            "bf16": {"enabled": True},
            "steps_per_print": 10_000,
        },
    )
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (engine.train_batch_size, seq), dtype=np.int32)}
    tok_per_sec = _train_tokens_per_sec(engine, batch, steps=5, warmup=2)
    return {
        "seq_len": seq,
        "tokens_per_sec_per_chip": round(tok_per_sec, 1),
        "mfu": round(tok_per_sec * cfg.flops_per_token(seq) / peak_flops, 4),
    }


def bench_train_fpdt_long_context(peak_flops):
    """FPDT chunked-attention TRAINING at 32k on one chip (round 5; reference
    fpdt_layer.py claims training sequences past attention's memory wall).

    The custom-VJP chunked attention holds O(S*chunk) score state instead of
    O(S^2): 32k would need ~12 GB of fp32 scores per (layer, head) pair dense,
    and the flash kernel's backward still rematerializes full rows; FPDT's
    tile recompute keeps the whole 125M-geometry model + 32k tokens resident
    on one v5e chip."""
    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    seq = 32768
    cfg = TransformerConfig(
        vocab_size=32000, hidden_size=768, intermediate_size=3072,
        num_layers=12, num_heads=12, max_seq_len=seq,
        norm="rmsnorm", activation="silu_glu", position="rope",
        attn_impl="fpdt", fpdt_q_chunk=2048, fpdt_kv_chunk=2048,
        remat=True, dtype=jax.numpy.bfloat16, scan_layers=False, fused_ce=False,
    )
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=seq),
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 1},
            "hbm_guard": {"enabled": True},
            "bf16": {"enabled": True},
            "steps_per_print": 10_000,
        },
    )
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (engine.train_batch_size, seq), dtype=np.int32)}
    tok_per_sec = _train_tokens_per_sec(engine, batch, steps=3, warmup=1)
    return {
        "seq_len": seq,
        "attn_impl": "fpdt",
        "tokens_per_sec_per_chip": round(tok_per_sec, 1),
        "mfu": round(tok_per_sec * cfg.flops_per_token(seq) / peak_flops, 4),
    }


def bench_train_fpdt_131k(peak_flops):
    """FPDT at 131072 tokens on ONE chip (stretch evidence for the
    reference's 16x-longer-sequences claim; fpdt_layer.py trains 2M tokens on
    four 40G GPUs with host offload — 131k on a single 16G v5e is the same
    regime). HBM math: 12 checkpointed [131k, 768] bf16 residuals ~2.4 GiB +
    fp32 Adam for 125M params ~1.5 GiB + per-chunk score state ~0.2 GiB."""
    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    seq = 131072
    cfg = TransformerConfig(
        vocab_size=32000, hidden_size=768, intermediate_size=3072,
        num_layers=12, num_heads=12, max_seq_len=seq,
        norm="rmsnorm", activation="silu_glu", position="rope",
        attn_impl="fpdt", fpdt_q_chunk=2048, fpdt_kv_chunk=2048,
        remat=True, dtype=jax.numpy.bfloat16, scan_layers=True, fused_ce=True,
    )
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=seq),
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 1},
            "hbm_guard": {"enabled": True},
            "bf16": {"enabled": True},
            "steps_per_print": 10_000,
        },
    )
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (1, seq), dtype=np.int32)}
    tok_per_sec = _train_tokens_per_sec(engine, batch, steps=2, warmup=1)
    return {
        "seq_len": seq,
        "attn_impl": "fpdt",
        "tokens_per_sec_per_chip": round(tok_per_sec, 1),
        "mfu": round(tok_per_sec * cfg.flops_per_token(seq) / peak_flops, 4),
    }


def bench_serving_overhead():
    """Host-side v2 serving overhead (tools/bench_serving.py): allocator,
    staged assembly, and host µs per decoded token at decode_chain 1 vs 8.
    Pure host work — wedge-proof, and the same numbers PERF.md's "serving
    overhead" section tracks."""
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "bench_serving.py")
    spec = importlib.util.spec_from_file_location("bench_serving", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    host = mod.bench_host_path()
    return {
        "host_us_per_decode_token_k1":
            host["per_token_loop"]["host_us_per_decode_token"],
        "host_us_per_decode_token_k8":
            host["chained"]["host_us_per_decode_token"],
        "host_us_speedup": host["host_us_speedup"],
        "programs_per_decode_token_k8":
            host["chained"]["programs_per_decode_token"],
        "allocator": mod.bench_allocator(),
        "assembly": mod.bench_assembly(),
    }


def bench_snapshot_overhead():
    """Step-time overhead of cadenced async elastic snapshots
    (``checkpoint/snapshot.py``) on the CPU bench model — the <2% bound
    ISSUE 6 commits to. Two identical engines (snapshots off / cadence-5
    async) step in PAIRED alternation — one off-step, one on-step, repeated
    over whole cadence cycles — so the CPU-frequency/load drift that swamps
    block timings (±15% observed between 10-step blocks on a shared host)
    hits both sides of every pair equally and cancels. The step program is
    byte-identical with snapshots on; the only step-clock cost is the
    boundary device→host copy (serialize + checksum + fsync + commit run in
    the writer thread)."""
    import shutil
    import tempfile

    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.checkpoint import snapshot as snap
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    cfg = TransformerConfig(
        vocab_size=512, hidden_size=128, intermediate_size=256,
        num_layers=2, num_heads=4, max_seq_len=256,
    )
    # micro 4 x seq 256: the step must be non-trivial for the ratio to mean
    # anything — the snapshot's synchronous cost (the boundary D2H copy of
    # the fp32 state) is FIXED per snapshot, so a toy 2-ms step at cadence 2
    # would measure the copy, not the amortized overhead a real cadence sees
    seq, micro, pairs, warmup, every = 256, 4, 60, 5, 5
    snap_dir = tempfile.mkdtemp(prefix="dstpu_snap_bench_")

    def build(snapshot_block):
        engine, *_ = deepspeed_tpu.initialize(
            model=causal_lm_spec(cfg, example_seq_len=seq),
            config={
                "train_micro_batch_size_per_gpu": micro,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
                "zero_optimization": {"stage": 1},
                "bf16": {"enabled": True},
                "steps_per_print": 10_000,
                **snapshot_block,
            })
        return engine

    try:
        e_off = build({})
        e_on = build({"snapshot": {"enabled": True, "dir": snap_dir,
                                   "every_n_steps": every, "keep": 2}})
        rng = np.random.default_rng(0)
        batch = {"input_ids": rng.integers(
            0, cfg.vocab_size, (e_off.train_batch_size, seq), dtype=np.int32)}

        def one_step(engine):
            t0 = time.perf_counter()
            m = engine.train_batch(batch)
            np.asarray(m["loss"])  # paired timing needs the per-step sync
            return time.perf_counter() - t0

        for e in (e_off, e_on):  # compile + first write outside the clock
            for _ in range(warmup):
                m = e.train_batch(batch)
            np.asarray(m["loss"])

        t_off = t_on = 0.0
        for _ in range(pairs):  # pairs % every == 0: whole cadence cycles
            t_off += one_step(e_off)
            t_on += one_step(e_on)
        e_on.snapshot_manager.wait()  # durability barrier outside the clock

        ms_off = t_off / pairs * 1e3
        ms_on = t_on / pairs * 1e3
        overhead_pct = (ms_on - ms_off) / ms_off * 100.0
        return {
            "model": "gpt2_cpu_bench_2L_128h_seq256_micro4",
            "snapshot_every_n_steps": every,
            "ms_per_step_snapshots_off": round(ms_off, 3),
            "ms_per_step_snapshots_on": round(ms_on, 3),
            "overhead_pct": round(overhead_pct, 2),
            "bound_pct": 2.0,
            "within_bound": bool(overhead_pct < 2.0),
            "snapshots_committed": len(snap.list_snapshots(snap_dir)),
        }
    finally:
        shutil.rmtree(snap_dir, ignore_errors=True)


def bench_compile_observability():
    """Host overhead of the compiled-program registry + telemetry
    (``telemetry/programs.py``) — the <2% bound ISSUE 7 commits to.

    One engine, built with telemetry AND program capture enabled, steps in
    PAIRED alternation with the process-global tracer flipped off/on around
    each step (same drift-cancelling discipline as the snapshot bench; the
    compiled program is identical either way, so the pair isolates exactly
    the host-side span/metric/watcher work). Program capture itself is paid
    once per compile — during warmup here — and is reported separately as
    ``capture_ms_total`` rather than smeared into the steady-state ratio."""
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu import telemetry as telemetry_mod
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec
    from deepspeed_tpu.telemetry.programs import get_program_registry

    cfg = TransformerConfig(
        vocab_size=512, hidden_size=128, intermediate_size=256,
        num_layers=2, num_heads=4, max_seq_len=256,
    )
    seq, micro, pairs, warmup = 256, 4, 50, 5
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=seq),
        config={
            "train_micro_batch_size_per_gpu": micro,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 1},
            "bf16": {"enabled": True},
            "steps_per_print": 10_000,
            "telemetry": {"enabled": True, "programs": True},
        })
    tracer = telemetry_mod.get_tracer()
    registry = get_program_registry()
    # the registry is process-global: earlier benches' captures must not
    # inflate this bench's reported counts/capture totals
    registry.reset()
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, cfg.vocab_size, (engine.train_batch_size, seq), dtype=np.int32)}

    for _ in range(warmup):  # compile + one-time program capture off the clock
        m = engine.train_batch(batch)
    np.asarray(m["loss"])

    def one_step(enabled):
        tracer.enabled = enabled
        t0 = time.perf_counter()
        m = engine.train_batch(batch)
        np.asarray(m["loss"])  # paired timing needs the per-step sync
        return time.perf_counter() - t0

    t_off = t_on = 0.0
    try:
        for _ in range(pairs):
            t_off += one_step(False)
            t_on += one_step(True)
    finally:
        tracer.enabled = True

    records = registry.history("train_step")
    ms_off = t_off / pairs * 1e3
    ms_on = t_on / pairs * 1e3
    overhead_pct = (ms_on - ms_off) / ms_off * 100.0
    return {
        "model": "gpt2_cpu_bench_2L_128h_seq256_micro4",
        "ms_per_step_telemetry_off": round(ms_off, 3),
        "ms_per_step_telemetry_on": round(ms_on, 3),
        "overhead_pct": round(overhead_pct, 2),
        "bound_pct": 2.0,
        "within_bound": bool(overhead_pct < 2.0),
        "programs_captured": len(registry.records()),
        "capture_ms_total": round(sum(r.capture_s for r in registry.records()) * 1e3, 1),
        "train_step_flops": records[-1].flops if records else 0.0,
        "train_step_peak_hbm_bytes": records[-1].peak_hbm_bytes if records else 0,
        "hbm_estimate_ratio": records[-1].hbm_estimate_ratio if records else None,
    }


def bench_moe_ep_tp():
    """MoE ep x tp composition micro-bench (ISSUE 15): per-step time of the
    collective token dispatch on a dp2 x ep2 x tp2 CPU mesh, exact wire vs
    the int8 quantized dispatch wire, plus the loss parity between them.

    CPU numbers measure DISPATCH/SCHEDULE structure, not interconnect (the
    wire win only exists on a real fabric) — the value here is trend
    evidence that the quantized path's program stays step-shaped (no
    per-hop host sync, no recompile churn) and numerically bounded. Needs
    >= 8 devices; records skipped otherwise."""
    import time as _time

    import numpy as np
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    if len(jax.devices()) < 8:
        return {"skipped": f"needs 8 devices, have {len(jax.devices())}"}

    def build(codec):
        # both arms force the SAME ring schedule so the reported ratio is
        # purely the wire codec's cost, never lax-vs-ring schedule delta
        cfg = TransformerConfig(
            vocab_size=512, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=4, max_seq_len=128, num_experts=4,
            moe_top_k=2, moe_capacity_factor=2.0,
            moe_dispatch_algorithm="ring",
            moe_wire_codec=codec)
        eng, *_ = deepspeed_tpu.initialize(
            model=causal_lm_spec(cfg, example_seq_len=64), config={
                "train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
                "zero_optimization": {"stage": 1},
                "mesh": {"dp": 2, "ep": 2, "tp": 2},
                "steps_per_print": 10_000,
            }, seed=5)
        return eng

    def clock(eng, steps=8, warmup=2):
        rng = np.random.default_rng(0)
        batch = {"input_ids": rng.integers(
            0, 512, (eng.train_batch_size, 64), dtype=np.int32)}
        losses = []
        for _ in range(warmup):
            eng.train_batch(batch)["loss"].block_until_ready()
        t0 = _time.perf_counter()
        for _ in range(steps):
            losses.append(eng.train_batch(batch)["loss"])
        losses[-1].block_until_ready()
        dt = (_time.perf_counter() - t0) / steps
        return dt * 1e3, float(losses[-1])

    exact_ms, exact_loss = clock(build(None))
    int8_ms, int8_loss = clock(build("int8"))
    return {
        "mesh": "dp2xep2xtp2",
        "step_ms_exact_wire": round(exact_ms, 2),
        "step_ms_int8_wire": round(int8_ms, 2),
        "int8_over_exact": round(int8_ms / exact_ms, 3) if exact_ms else None,
        "loss_rel_gap": round(abs(int8_loss - exact_loss)
                              / max(abs(exact_loss), 1e-9), 6),
        "degraded": True,  # CPU: structure evidence, not interconnect perf
    }


def bench_coll_observability():
    """Host overhead of the collective observatory's timing mode
    (``collectives/observatory.py``) — the <2% bound ISSUE 11 commits to,
    same paired-step discipline as the PR-5/PR-7 overhead guards.

    ONE engine built with the ``collectives.observe`` block enabled steps in
    PAIRED alternation with the observatory flipped off/on around each step.
    A routed collective signature is registered on the engine's mesh before
    the clock (the PR-1 comm-probe idiom), so enabled steps pay the real
    ``on_step`` hook INCLUDING sampled probe dispatches at the configured
    cadence; probe compiles happen during warmup (``sample_now``), never on
    the clock. ``pairs`` is a whole number of cadence cycles so off/on see
    identical probe phases."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import deepspeed_tpu
    import deepspeed_tpu.comm as dist_mod
    from deepspeed_tpu.collectives import observatory
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec
    from deepspeed_tpu.utils.compat import shard_map

    cfg = TransformerConfig(
        vocab_size=512, hidden_size=128, intermediate_size=256,
        num_layers=2, num_heads=4, max_seq_len=256,
    )
    seq, micro, sample_every, pairs, warmup = 256, 4, 4, 48, 5
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=seq),
        config={
            "train_micro_batch_size_per_gpu": micro,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 1},
            "bf16": {"enabled": True},
            "steps_per_print": 10_000,
            "collectives": {"enabled": True,
                            "observe": {"enabled": True,
                                        "sample_every": sample_every,
                                        "persist": False,
                                        "refit_every": 0}},
        })
    obs = engine._coll_observatory
    assert obs is not None
    # register one routed signature on the engine's mesh (the GSPMD step
    # has no explicit facade collective to observe — PR-8 note), so probes
    # have something real to time
    axis = "dp"
    n = int(engine.mesh.shape[axis])
    probe = jax.jit(shard_map(
        lambda v: dist_mod.all_reduce(v, axis, algorithm="ring", codec="int8",
                                      block_size=256),
        mesh=engine.mesh, in_specs=P(axis), out_specs=P(axis),
        check_vma=False))
    probe(jnp.ones((n * n * 256,), jnp.float32)).block_until_ready()
    probes_warm = obs.sample_now()  # probe compiles off the clock

    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, cfg.vocab_size, (engine.train_batch_size, seq), dtype=np.int32)}
    for _ in range(warmup):
        m = engine.train_batch(batch)
    np.asarray(m["loss"])

    def one_step(enabled):
        obs.config.enabled = enabled
        t0 = time.perf_counter()
        m = engine.train_batch(batch)
        np.asarray(m["loss"])  # paired timing needs the per-step sync
        return time.perf_counter() - t0

    t_off = t_on = 0.0
    try:
        for _ in range(pairs):
            t_off += one_step(False)
            t_on += one_step(True)
    finally:
        obs.config.enabled = True

    s = obs.summary()
    ms_off = t_off / pairs * 1e3
    ms_on = t_on / pairs * 1e3
    overhead_pct = (ms_on - ms_off) / ms_off * 100.0
    return {
        "model": "gpt2_cpu_bench_2L_128h_seq256_micro4",
        "sample_every": sample_every,
        "ms_per_step_observatory_off": round(ms_off, 3),
        "ms_per_step_observatory_on": round(ms_on, 3),
        "overhead_pct": round(overhead_pct, 2),
        "bound_pct": 2.0,
        "within_bound": bool(overhead_pct < 2.0),
        "probes_warmup": probes_warm,
        "probes_merged": s["merged_samples"],
        "table_rows": s["table_rows"],
        "routes": s["routes"],
    }


def bench_fleet_overhead():
    """Host overhead of fleet telemetry export (``telemetry/collector.py``)
    — the <2% bound ISSUE 13 commits to, same paired-step discipline as the
    PR-5/7/11 guards.

    ONE telemetry-enabled engine steps in paired off/on alternation against
    a live in-process :class:`FleetCollector`; every ``cadence``-th on-step
    pays a ``FleetClient.push_async`` on the clock — the hot-path push API:
    the registry dump + heartbeat snapshot happens synchronously (the cost
    a step actually sees) and the HTTP round-trip rides the client's worker
    thread, exactly like the production daemon-cadence wiring. Cadence 5
    per STEP is far denser than the config default (a 5-second wall-clock
    interval), so the bound holds with margin for any real deployment."""
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec
    from deepspeed_tpu.telemetry.collector import FleetClient, FleetCollector

    cfg = TransformerConfig(
        vocab_size=512, hidden_size=128, intermediate_size=256,
        num_layers=2, num_heads=4, max_seq_len=256,
    )
    seq, micro, cadence, pairs, warmup = 256, 4, 5, 60, 5
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=seq),
        config={
            "train_micro_batch_size_per_gpu": micro,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 1},
            "bf16": {"enabled": True},
            "steps_per_print": 10_000,
            "telemetry": {"enabled": True},
        })
    collector = FleetCollector().start()
    client = FleetClient(collector.url, observatory=None)
    client.register()

    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, cfg.vocab_size, (engine.train_batch_size, seq), dtype=np.int32)}
    for _ in range(warmup):
        m = engine.train_batch(batch)
    np.asarray(m["loss"])
    client.push(include_table=False)  # first push (lazy setup) off the clock

    on_steps = [0]

    def one_step(push):
        t0 = time.perf_counter()
        m = engine.train_batch(batch)
        if push:
            on_steps[0] += 1
            if on_steps[0] % cadence == 0:
                client.push_async(include_table=False)
        np.asarray(m["loss"])  # paired timing needs the per-step sync
        return time.perf_counter() - t0

    try:
        t_off = t_on = 0.0
        for _ in range(pairs):  # pairs % cadence == 0: whole push cycles
            t_off += one_step(False)
            t_on += one_step(True)
        client.flush()  # drain the async worker off the clock
    finally:
        collector.stop()

    ms_off = t_off / pairs * 1e3
    ms_on = t_on / pairs * 1e3
    overhead_pct = (ms_on - ms_off) / ms_off * 100.0
    return {
        "model": "gpt2_cpu_bench_2L_128h_seq256_micro4",
        "push_every_n_steps": cadence,
        "ms_per_step_fleet_off": round(ms_off, 3),
        "ms_per_step_fleet_on": round(ms_on, 3),
        "overhead_pct": round(overhead_pct, 2),
        "bound_pct": 2.0,
        "within_bound": bool(overhead_pct < 2.0),
        "pushes": client.pushes,
        "push_failures": client.push_failures,
        "federated_metric_children": collector.federated_registry().size(),
    }


def bench_event_plane_overhead():
    """Host overhead of the incident plane (``telemetry/events.py`` +
    ``telemetry/alerts.py``) — the <2% bound ISSUE 20 commits to, same
    paired-step discipline as the PR-5/7/11/13/16 guards.

    On-steps emit one typed structured event right after the loss sync
    (lock + ring append + counter mints + per-subscriber fanout) and every
    ``cadence``-th on-step pays a full ``AlertEngine.evaluate()`` over the
    default rule pack on the clock — the exact host work a detector site
    and the alert cadence thread add to a production step. One event per
    STEP plus an evaluate every 5 steps is far denser than any real run
    (detectors only emit on anomalies; the cadence thread defaults to a
    5-second wall-clock interval), so the bound holds with margin. The
    step program itself never changes: emission is host-side only, which
    the jaxpr-identity pin in tests/unit/test_events_alerts.py enforces."""
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec
    from deepspeed_tpu.telemetry import alerts as alerts_mod
    from deepspeed_tpu.telemetry import events as events_mod

    cfg = TransformerConfig(
        vocab_size=512, hidden_size=128, intermediate_size=256,
        num_layers=2, num_heads=4, max_seq_len=256,
    )
    seq, micro, cadence, pairs, warmup = 256, 4, 5, 60, 5
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=seq),
        config={
            "train_micro_batch_size_per_gpu": micro,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 1},
            "bf16": {"enabled": True},
            "steps_per_print": 10_000,
            "telemetry": {"enabled": True},
        })
    stream = events_mod.configure_events(capacity=4096, jsonl_path=None)
    stream.clear()
    alert_eng = alerts_mod.configure_alerts()  # default rule pack, no sinks
    emit = events_mod.emit_event

    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, cfg.vocab_size, (engine.train_batch_size, seq), dtype=np.int32)}
    for _ in range(warmup):
        m = engine.train_batch(batch)
    np.asarray(m["loss"])
    alert_eng.evaluate()  # first evaluate (lazy rule state) off the clock

    on_steps = [0]

    def one_step(plane_on):
        t0 = time.perf_counter()
        m = engine.train_batch(batch)
        if plane_on:
            on_steps[0] += 1
            emit("bench", "step_tick",
                 f"bench event-plane tick {on_steps[0]}", severity="info",
                 labels={"bench": "event_plane_overhead"}, step=on_steps[0])
            if on_steps[0] % cadence == 0:
                alert_eng.evaluate()
        np.asarray(m["loss"])  # paired timing needs the per-step sync
        return time.perf_counter() - t0

    t_off = t_on = 0.0
    for _ in range(pairs):  # pairs % cadence == 0: whole evaluate cycles
        t_off += one_step(False)
        t_on += one_step(True)

    ms_off = t_off / pairs * 1e3
    ms_on = t_on / pairs * 1e3
    overhead_pct = (ms_on - ms_off) / ms_off * 100.0
    return {
        "model": "gpt2_cpu_bench_2L_128h_seq256_micro4",
        "evaluate_every_n_steps": cadence,
        "ms_per_step_events_off": round(ms_off, 3),
        "ms_per_step_events_on": round(ms_on, 3),
        "overhead_pct": round(overhead_pct, 2),
        "bound_pct": 2.0,
        "within_bound": bool(overhead_pct < 2.0),
        "events_emitted": stream.total_emitted,
        "alert_rules": len(alert_eng.rules),
        "firing_alerts": [f["rule"] for f in alert_eng.firing()],
    }


def bench_perf_ledger_overhead():
    """Row-emission overhead of the unified perf ledger
    (``telemetry/perfledger.py``) — the <2% bound ISSUE 16 commits to, same
    paired-step discipline as the PR-5/7/11/13 guards.

    On-steps append one identity-stamped schema-v1 row to a REAL JSONL
    ledger (tempdir) right after the loss sync — the exact emit an
    instrumented bench or serving run pays per measurement: make_row's
    stamping (identity, git sha, backend) plus validate + lock + open +
    append + fsync-free write. One row per step is far denser than any real
    emitter (one row per whole run), so the bound holds with margin."""
    import os
    import tempfile

    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec
    from deepspeed_tpu.telemetry.perfledger import (
        PerfLedger, make_row, resolve_git_sha,
    )

    cfg = TransformerConfig(
        vocab_size=512, hidden_size=128, intermediate_size=256,
        num_layers=2, num_heads=4, max_seq_len=256,
    )
    seq, micro, pairs, warmup = 256, 4, 60, 5
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=seq),
        config={
            "train_micro_batch_size_per_gpu": micro,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 1},
            "bf16": {"enabled": True},
            "steps_per_print": 10_000,
        })
    ledger = PerfLedger(tempfile.mkdtemp(prefix="perf_ledger_bench_"))
    resolve_git_sha()  # warm the one subprocess stamp off the clock

    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, cfg.vocab_size, (engine.train_batch_size, seq), dtype=np.int32)}
    for _ in range(warmup):
        m = engine.train_batch(batch)
    np.asarray(m["loss"])
    ledger.append([make_row("perf", "ledger_probe/loss", 0.0, "nats",
                            direction="lower")])  # lazy mkdir off the clock

    def one_step(emit):
        t0 = time.perf_counter()
        m = engine.train_batch(batch)
        loss = float(np.asarray(m["loss"]))  # paired timing needs the sync
        if emit:
            ledger.append([make_row("perf", "ledger_probe/loss", loss,
                                    "nats", direction="lower")])
        return time.perf_counter() - t0

    t_off = t_on = 0.0
    for _ in range(pairs):
        t_off += one_step(False)
        t_on += one_step(True)

    ms_off = t_off / pairs * 1e3
    ms_on = t_on / pairs * 1e3
    overhead_pct = (ms_on - ms_off) / ms_off * 100.0
    return {
        "model": "gpt2_cpu_bench_2L_128h_seq256_micro4",
        "rows_emitted": pairs + 1,
        "ledger_bytes": os.path.getsize(ledger.path_for("perf")),
        "ms_per_step_ledger_off": round(ms_off, 3),
        "ms_per_step_ledger_on": round(ms_on, 3),
        "overhead_pct": round(overhead_pct, 2),
        "bound_pct": 2.0,
        "within_bound": bool(overhead_pct < 2.0),
    }


def bench_numerics_overhead():
    """Step-time overhead of the numerics observatory
    (``telemetry/numerics.py``): in-jit divergence sentinel + sampled wire
    probes + host hook — the <2% bound ISSUE 17 commits to.

    Unlike the host-flag overhead benches, the sentinel is TRACED into the
    step, so off/on are two engines (identical config, numerics block
    absent vs enabled) stepping the same batch in paired alternation.
    Reported worst-of-three rounds: the bound must hold on the worst round,
    not a lucky mean. One routed lossy signature is registered before the
    clock so sampled steps pay real wire-probe dispatches (compiles happen
    during ``sample_now`` warmup, never on the clock)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import deepspeed_tpu
    import deepspeed_tpu.comm as dist_mod
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec
    from deepspeed_tpu.telemetry import numerics
    from deepspeed_tpu.utils.compat import shard_map

    cfg = TransformerConfig(
        vocab_size=512, hidden_size=128, intermediate_size=256,
        num_layers=2, num_heads=4, max_seq_len=256,
    )
    seq, micro, sample_every, warmup = 256, 4, 4, 5
    rounds, pairs = 3, 16  # pairs per round: whole cadence cycles

    def build(numerics_block):
        engine, *_ = deepspeed_tpu.initialize(
            model=causal_lm_spec(cfg, example_seq_len=seq),
            config={
                "train_micro_batch_size_per_gpu": micro,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
                "zero_optimization": {"stage": 1},
                "bf16": {"enabled": True},
                "steps_per_print": 10_000,
                **({"numerics": numerics_block} if numerics_block else {}),
            })
        return engine

    # baseline FIRST: a no-numerics engine resets the process-global
    # observatory on construction (hygiene), so the enabled engine must be
    # built after it
    eng_off = build(None)
    eng_on = build({"enabled": True, "sample_every": sample_every,
                    "sentinel_sample_every": sample_every})
    obs = numerics.get_observatory()
    # a routed lossy signature so sampled steps run a real fidelity probe
    axis = "dp"
    n = int(eng_on.mesh.shape[axis])
    probe = jax.jit(shard_map(
        lambda v: dist_mod.all_reduce(v, axis, algorithm="ring", codec="int8",
                                      block_size=256),
        mesh=eng_on.mesh, in_specs=P(axis), out_specs=P(axis),
        check_vma=False))
    probe(jnp.ones((n * n * 256,), jnp.float32)).block_until_ready()
    obs.sample_now()  # probe compiles off the clock

    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, cfg.vocab_size, (eng_on.train_batch_size, seq), dtype=np.int32)}
    for _ in range(warmup):
        m_off = eng_off.train_batch(batch)
        m_on = eng_on.train_batch(batch)
    np.asarray(m_off["loss"]), np.asarray(m_on["loss"])

    def one_step(engine):
        t0 = time.perf_counter()
        m = engine.train_batch(batch)
        np.asarray(m["loss"])  # paired timing needs the per-step sync
        return time.perf_counter() - t0

    round_pcts, ms_offs, ms_ons = [], [], []
    for _ in range(rounds):
        t_off = t_on = 0.0
        for _ in range(pairs):
            t_off += one_step(eng_off)
            t_on += one_step(eng_on)
        ms_offs.append(t_off / pairs * 1e3)
        ms_ons.append(t_on / pairs * 1e3)
        round_pcts.append((t_on - t_off) / t_off * 100.0)

    worst = max(round_pcts)
    return {
        "model": "gpt2_cpu_bench_2L_128h_seq256_micro4",
        "sample_every": sample_every,
        "sentinel_sample_every": sample_every,
        "rounds": rounds,
        "pairs_per_round": pairs,
        "ms_per_step_numerics_off": round(min(ms_offs), 3),
        "ms_per_step_numerics_on": round(min(ms_ons), 3),
        "overhead_pct": round(sum(round_pcts) / rounds, 2),
        "overhead_pct_max": round(worst, 2),
        "bound_pct": 2.0,
        "within_bound": bool(worst < 2.0),
        "divergence_events": obs.divergence_events_seen,
        "wire_drift_events": obs.wire_drift_events,
        "routes": len(obs.routes()),
    }


def bench_schedule_compiler():
    """ISSUE 19 evidence: the two ``schedule``-suite headline rows.

    1. ``compiled_vs_hand/pred_ratio`` — the schedule compiler's best
       synthesized program vs the best hand-written algorithm, both costed
       by the SAME (possibly refit-calibrated) cost model, at the
       representative int8 1 MB all_reduce query. Drifting UP means the
       search started losing to its own baseline — a compiler regression
       the noise-aware gate catches without any hardware in the loop.
    2. ``fused_gemm/step_time_ratio`` — fused all-gather+matmul forward+
       backward step vs the unfused composition on the live backend (the
       T3 payoff row; on TPU < 1.0 is the win, interpret-mode CPU values
       are per-backend trajectories only).

    Rows go straight to perf-ledger suite ``schedule``
    (``perfgate.HEADLINE_PATTERNS["schedule"]``), like the sweep's
    ``coll-sweep`` rows."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from deepspeed_tpu.collectives import fused_gemm, schedule, selector
    from deepspeed_tpu.collectives.algorithms import ALGORITHMS
    from deepspeed_tpu.parallel import zeropp
    from deepspeed_tpu.utils.compat import shard_map

    devs = jax.devices()
    n = max(len(devs), 1)
    nbytes, codec = 1 << 20, "int8"
    cm = selector.cost_model()
    hand = min(
        selector.estimate_us("all_reduce", alg, codec, nbytes, n)
        for alg in ALGORITHMS
        if not (alg == "rhd" and (n & (n - 1))))
    sched = schedule.compile_schedule("all_reduce", (("dp", n),), nbytes,
                                      codec, cm=cm)
    pred_ratio = (sched.est_us / hand) if (sched and hand > 0) else 1.0

    mesh = Mesh(np.array(devs), ("fsdp",))
    M, Ks, N = 64, 64, 128
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(M, n * Ks)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(n * Ks, N)).astype(np.float32))

    def step_fn(xv, wv):
        def loss(a, b):
            y = zeropp.sharded_matmul(a, b, "fsdp", False, 256)
            return jnp.sum(y * y)

        return jax.grad(loss, argnums=1)(xv, wv)

    def clock(fused):
        fused_gemm.configure(enabled=fused)
        f = jax.jit(shard_map(step_fn, mesh=mesh, in_specs=(P(), P("fsdp")),
                              out_specs=P("fsdp"), check_vma=False))
        np.asarray(f(x, w))  # compile off the clock
        t0 = time.perf_counter()
        for _ in range(5):
            out = f(x, w)
        np.asarray(out)
        return time.perf_counter() - t0

    try:
        t_unfused = clock(False)
        t_fused = clock(True)
    finally:
        fused_gemm.configure(enabled=False)
    step_ratio = t_fused / t_unfused if t_unfused > 0 else 1.0

    result = {
        "world": n,
        "compiled_signature": sched.signature if sched else None,
        "compiled_pred_us": round(sched.est_us, 3) if sched else None,
        "hand_pred_us": round(hand, 3),
        "pred_ratio": round(pred_ratio, 4),
        "ms_step_unfused": round(t_unfused / 5 * 1e3, 3),
        "ms_step_fused": round(t_fused / 5 * 1e3, 3),
        "step_time_ratio": round(step_ratio, 4),
    }
    try:
        from deepspeed_tpu.telemetry.perfledger import PerfLedger, make_row

        backend = jax.default_backend()
        PerfLedger().append([
            make_row("schedule", "compiled_vs_hand/pred_ratio", pred_ratio,
                     "ratio", direction="lower", backend=backend),
            make_row("schedule", "fused_gemm/step_time_ratio", step_ratio,
                     "ratio", direction="lower", backend=backend),
        ])
    except Exception as e:  # noqa: BLE001 — evidence plane, not the bench
        import sys

        print(f"[bench] schedule-suite ledger append skipped: {e}",
              file=sys.stderr)
    return result


# Confidence-ordered registry (safest first): a relay wedge mid-queue loses
# everything after it, so known-good shapes go first and the big/novel
# configs last. Each entry: name -> (fn(peak_flops)->dict, timeout_s).
EXTRA_BENCHES = {
    "serving_overhead_host": (lambda peak: bench_serving_overhead(), 420),
    "elastic_snapshot_overhead": (lambda peak: bench_snapshot_overhead(), 420),
    "compile_observability": (lambda peak: bench_compile_observability(), 420),
    "coll_observability": (lambda peak: bench_coll_observability(), 420),
    "fleet_export_overhead": (lambda peak: bench_fleet_overhead(), 420),
    "event_plane_overhead": (lambda peak: bench_event_plane_overhead(), 420),
    "perf_ledger_overhead": (lambda peak: bench_perf_ledger_overhead(), 420),
    "numerics_overhead": (lambda peak: bench_numerics_overhead(), 420),
    "schedule_compiler": (lambda peak: bench_schedule_compiler(), 420),
    "llama_550m_zero3_remat": (bench_train_llama_z3, 420),
    "mixtral_style_moe": (bench_train_moe, 420),
    "inference_v1_gpt2_125m": (lambda peak: bench_inference(), 420),
    "inference_v2_ragged_gpt2_125m": (lambda peak: bench_inference_v2(), 480),
    "inference_v1_llama_550m": (lambda peak: bench_inference_llama(), 480),
    "long_context_8k": (bench_train_long_context, 480),
    "fpdt_long_context_32k": (bench_train_fpdt_long_context, 600),
    "nvme_offload_550m": (bench_train_nvme_offload, 600),
    "dense_760m_zero3_remat": (bench_train_dense_1b, 600),
    "dense_2b_offload_host": (bench_train_dense_2b_offload, 600),
    "dense_2b_offload_twinflow": (bench_train_dense_2b_twinflow, 600),
    "fpdt_long_context_131k": (bench_train_fpdt_131k, 900),
}


def _child_main(name: str) -> None:
    """Child-process entry (``bench.py --one NAME``): run exactly one
    benchmark on the already-probed TPU and print its result as the LAST
    stdout line. Isolation exists because a bad config (e.g. an HBM OOM
    during param materialization) can wedge the axon relay RPC forever
    rather than raise — observed round 5 with the original 890M sizing —
    and a wedge inside the single bench process would hang the driver's
    end-of-round run."""
    import sys

    import jax

    if jax.default_backend() != "tpu":
        # The parent probed TPU-up; if this child still fell back (e.g. the
        # lease vanished between probe and spawn) its numbers must NEVER be
        # reported as undegraded TPU results — fail loudly instead.
        print(f"child backend is {jax.default_backend()!r}, not tpu",
              file=sys.stderr)
        raise SystemExit(2)
    peak_flops = PEAK_FLOPS_TPU
    if name == "_headline":
        tok_per_sec, mfu, seq, stamp, telem = bench_train_gpt2(True, peak_flops)
        out = {"tok_per_sec": tok_per_sec, "mfu": mfu, "seq": seq,
               "autotuned": stamp,
               **({"telemetry": telem} if telem else {})}
    else:
        out = EXTRA_BENCHES[name][0](peak_flops)
    print(json.dumps(out), flush=True)


def _run_isolated(name: str, timeout_s: float):
    """Run one benchmark in a subprocess; return (parsed_json | None, error).

    On timeout the whole child process group is killed (the TPU runtime forks
    helpers that would otherwise keep the device lease)."""
    import os
    import signal
    import subprocess
    import sys

    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--one", name],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, start_new_session=True,
    )
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except OSError:
            pass
        proc.wait()
        return None, f"timeout after {timeout_s:.0f}s (relay wedge?)"
    for line in reversed((out or "").strip().splitlines()):
        try:
            parsed = json.loads(line)
        except ValueError:
            continue
        if isinstance(parsed, dict):  # a stray scalar print is not a result
            return parsed, None
    # keep the child's actual exception — on flaky relay hardware these
    # strings are the primary evidence for what went wrong
    tail = " | ".join((err or "").strip().splitlines()[-4:])[-600:]
    return None, f"exit code {proc.returncode}: {tail or 'no JSON on stdout'}"


def _probe_tpu(timeout_s: float = 180.0) -> bool:
    """True iff the TPU backend initializes within timeout_s.

    A wedged relay (stale lease after a killed process) makes jax.devices()
    hang for MINUTES with no exception — probing in a subprocess keeps this
    process clean so it can fall back to the CPU smoke bench instead of
    hanging forever. Must run BEFORE jax is imported in this process."""
    import os
    import signal
    import subprocess
    import sys

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        return False  # explicitly CPU-pinned (tests): nothing to probe
    # DEVNULL + new session: a wedged child's TPU-runtime grandchildren must
    # not inherit pipes we would block draining, and the timeout kill must
    # take the whole process group down.
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "import jax, sys; sys.exit(0 if jax.default_backend() == 'tpu' else 1)"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        start_new_session=True,
    )
    try:
        return proc.wait(timeout=timeout_s) == 0
    except subprocess.TimeoutExpired:
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except OSError:
            pass
        return False


def _emit_perf_ledger(result: dict, backend: str) -> None:
    """Append this run's numbers to the unified perf ledger alongside the
    legacy JSON line (ISSUE 16): the headline into suite ``bench`` (the
    same two rows migration derives from a BENCH_rNN artifact), every
    numeric leaf of each successful extra into suite ``perf`` under
    ``<extra>/<path>`` — so the ``*overhead_pct`` rows land under the
    gate's absolute <2% bound automatically. Best-effort: the bench must
    never fail because the ledger dir is unwritable."""
    import sys

    try:
        from deepspeed_tpu.telemetry.perfledger import PerfLedger, make_row
        from deepspeed_tpu.telemetry.perfmigrate import (
            direction_for, flatten_numeric, unit_for,
        )

        rows = [make_row("bench", result["metric"], result["value"],
                         result["unit"], backend=backend)]
        if "vs_baseline" in result:
            rows.append(make_row("bench", f"{result['metric']}/vs_baseline",
                                 result["vs_baseline"], "ratio",
                                 backend=backend))
        for name, extra in (result.get("extras") or {}).items():
            if not isinstance(extra, dict) or "error" in extra:
                continue
            for path, value in flatten_numeric(extra):
                metric = f"{name}/{path}"
                rows.append(make_row("perf", metric, value, unit_for(metric),
                                     direction_for(metric), backend=backend))
        PerfLedger().append(rows)
    except Exception as e:  # noqa: BLE001 — evidence plane, not the bench
        print(f"[bench] perf-ledger append skipped: {e}", file=sys.stderr)


def _main_tpu() -> None:
    """TPU orchestrator: the parent never imports jax (so it never holds the
    device lease) — every benchmark runs in its own timeout-guarded child.
    After any timeout, a quick re-probe decides whether the relay survived;
    once it's gone the remaining extras are recorded as skipped instead of
    each burning its own timeout."""
    headline, err = _run_isolated("_headline", 900)
    if headline is None and _probe_tpu(120):
        headline, err = _run_isolated("_headline", 900)  # one retry
    if headline is None:
        raise RuntimeError(f"headline: {err}")

    extras, relay_dead = {}, False
    for name, (_, timeout_s) in EXTRA_BENCHES.items():
        if relay_dead:
            extras[name] = {"error": "skipped: relay wedged earlier in the run"}
            continue
        out, err = _run_isolated(name, timeout_s)
        if out is not None:
            extras[name] = out
        else:
            extras[name] = {"error": err}
            if "timeout" in err:
                relay_dead = not _probe_tpu(120)

    stamp = headline.get("autotuned")
    result = {
        "metric": f"tokens_per_sec_per_chip_gpt2_125m_bf16_seq{headline['seq']}",
        "value": round(headline["tok_per_sec"], 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(headline["mfu"] / 0.45, 4),
        **({"autotuned": stamp} if stamp else {}),
        **({"telemetry": headline["telemetry"]} if headline.get("telemetry") else {}),
        "extras": extras,
    }
    print(json.dumps(result))
    _emit_perf_ledger(result, backend="tpu-v5e")


def main() -> None:
    import os
    import sys

    if len(sys.argv) > 2 and sys.argv[1] == "--one":
        _child_main(sys.argv[2])
        return

    degraded = os.environ.get("DSTPU_BENCH_DEGRADED") == "1"
    if not degraded:
        if _probe_tpu():
            try:
                _main_tpu()
                return
            except RuntimeError:
                # headline never completed on chip (wedge mid-run): fall
                # through to the degraded CPU smoke so the bench still emits
                # its line.
                pass
        os.environ["DSTPU_BENCH_DEGRADED"] = "1"
        # Fall back to CPU so the bench always emits its JSON line — by
        # re-running in a child with JAX_PLATFORMS pinned BEFORE its
        # interpreter starts, so no jax-internal surgery is needed. A
        # subprocess (not execve) keeps `import bench; bench.main()` callers
        # alive, forwards argv, and lets an exec failure still fall through
        # to the in-process path below. DSTPU_BENCH_DEGRADED both skips the
        # (already failed) probe in the child and stamps its output.
        import subprocess

        env = dict(os.environ, JAX_PLATFORMS="cpu", DSTPU_BENCH_DEGRADED="1")
        try:
            sys.exit(subprocess.call(
                [sys.executable, os.path.abspath(__file__), *sys.argv[1:]], env=env))
        except OSError:
            os.environ["JAX_PLATFORMS"] = "cpu"
            os.environ["DSTPU_BENCH_DEGRADED"] = "1"
            degraded = True
    if degraded:
        import jax

        # Belt and suspenders: if something imported jax before the env var
        # latched (sitecustomize), force the live config too — and DROP the
        # axon backend factory: with the factory registered, the first
        # computation can initialize the plugin and block on the wedged relay
        # even under JAX_PLATFORMS=cpu (observed round 5).
        from deepspeed_tpu.utils.cpu_backend import force_cpu_backend

        force_cpu_backend()
    import jax

    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    peak_flops = PEAK_FLOPS_TPU if on_tpu else PEAK_FLOPS_CPU_SMOKE

    # The TPU path (with extras) lives in _main_tpu(); reaching here means
    # CPU smoke only.
    tok_per_sec, mfu, seq, autotuned_stamp, telem = bench_train_gpt2(on_tpu, peak_flops)

    extras = {}
    # Host-side serving overhead is measurable without the TPU (the point:
    # inference perf evidence that doesn't need the relay, VERDICT r5 #5).
    try:
        extras["serving_overhead_host"] = bench_serving_overhead()
    except Exception as e:  # noqa: BLE001 — smoke bench must still emit
        extras["serving_overhead_host"] = {"error": str(e)[:200]}
    # Async-snapshot step-time overhead is host+disk work around an
    # unchanged step program — CPU-measurable, same <2% bound as on chip.
    try:
        extras["elastic_snapshot_overhead"] = bench_snapshot_overhead()
    except Exception as e:  # noqa: BLE001
        extras["elastic_snapshot_overhead"] = {"error": str(e)[:200]}
    # Program-registry + telemetry host overhead around an identical step
    # program — CPU-measurable, same <2% bound as on chip (ISSUE 7).
    try:
        extras["compile_observability"] = bench_compile_observability()
    except Exception as e:  # noqa: BLE001
        extras["compile_observability"] = {"error": str(e)[:200]}
    # Collective-observatory timing-mode overhead around an unchanged step
    # program — CPU-measurable, same <2% bound as on chip (ISSUE 11).
    try:
        extras["coll_observability"] = bench_coll_observability()
    except Exception as e:  # noqa: BLE001
        extras["coll_observability"] = {"error": str(e)[:200]}
    # Fleet-export overhead (collector push + heartbeat around an unchanged
    # step program) is pure host+localhost-HTTP work — CPU-measurable, same
    # <2% bound as on chip (ISSUE 13).
    try:
        extras["fleet_export_overhead"] = bench_fleet_overhead()
    except Exception as e:  # noqa: BLE001
        extras["fleet_export_overhead"] = {"error": str(e)[:200]}
    # Incident-plane overhead (typed event emit per step + default-rule
    # alert evaluate every 5 steps around an unchanged step program) is
    # pure host work — CPU-measurable, same <2% bound as on chip (ISSUE 20).
    try:
        extras["event_plane_overhead"] = bench_event_plane_overhead()
    except Exception as e:  # noqa: BLE001
        extras["event_plane_overhead"] = {"error": str(e)[:200]}
    # MoE ep x tp collective dispatch: step-shape + numeric-bound evidence
    # for the quantized token wire (ISSUE 15); needs the 8-device CPU mesh.
    try:
        extras["moe_ep_tp"] = bench_moe_ep_tp()
    except Exception as e:  # noqa: BLE001
        extras["moe_ep_tp"] = {"error": str(e)[:200]}
    # Perf-ledger row emission around an unchanged step program is pure
    # host+disk work — CPU-measurable, same <2% bound as on chip (ISSUE 16).
    try:
        extras["perf_ledger_overhead"] = bench_perf_ledger_overhead()
    except Exception as e:  # noqa: BLE001
        extras["perf_ledger_overhead"] = {"error": str(e)[:200]}
    result = {
        "metric": f"tokens_per_sec_per_chip_gpt2_125m_bf16_seq{seq}" if on_tpu
        else f"tokens_per_sec_cpu_smoke_seq{seq}",
        "value": round(tok_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.45, 4),
        # A CPU-smoke number is NOT comparable to the TPU headline: stamp it
        # so trend tooling reading only vs_baseline can't mistake a wedged
        # relay for a 15x regression (round-3 verdict, weak item 1).
        **({"degraded": True} if not on_tpu else {}),
        **({"autotuned": autotuned_stamp} if autotuned_stamp else {}),
        **({"telemetry": telem} if telem else {}),
        **({"extras": extras} if extras else {}),
    }
    print(json.dumps(result))
    _emit_perf_ledger(result, backend="tpu-v5e" if on_tpu else "cpu")


if __name__ == "__main__":
    main()
