"""Benchmark: flagship CausalLM training throughput on the local accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

On the single real TPU chip this measures tokens/sec/chip for GPT-2-small
(125M params, bf16, seq 1024) full train steps (fwd+bwd+Adam) through the
engine. vs_baseline = achieved MFU / 0.45, the north-star MFU from
BASELINE.md (reference's Ulysses/FPDT blogs claim ~54%/55% peak on A100;
this repo's target is >=45% MFU on TPU).

Falls back to a tiny model on CPU so the bench always completes.
"""

from __future__ import annotations

import json
import sys
import time


def main() -> None:
    import jax

    backend = jax.default_backend()
    on_tpu = backend == "tpu"

    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, causal_lm_spec

    if on_tpu:
        cfg = TransformerConfig(
            vocab_size=50304, hidden_size=768, intermediate_size=3072,
            num_layers=12, num_heads=12, max_seq_len=1024,
            norm="layernorm", activation="gelu", position="learned",
            tie_embeddings=True, dtype=jax.numpy.bfloat16,
        )
        micro, seq, steps, warmup = 8, 1024, 10, 3
        peak_flops = 197e12  # v5e bf16 peak per chip
    else:
        cfg = TransformerConfig(
            vocab_size=512, hidden_size=128, intermediate_size=256,
            num_layers=2, num_heads=4, max_seq_len=256,
        )
        micro, seq, steps, warmup = 2, 128, 3, 1
        peak_flops = 1e12  # nominal; CPU numbers are smoke-test only

    gas = 4 if on_tpu else 1
    config = {
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4, "weight_decay": 0.1}},
        "zero_optimization": {"stage": 1},
        "bf16": {"enabled": True},
        "gradient_clipping": 1.0,
        "steps_per_print": 10_000,
    }
    engine, *_ = deepspeed_tpu.initialize(
        model=causal_lm_spec(cfg, example_seq_len=seq), config=config
    )

    rng = np.random.default_rng(0)
    batch = {
        "input_ids": rng.integers(0, cfg.vocab_size, (engine.train_batch_size, seq), dtype=np.int32)
    }

    # NOTE: sync via an explicit scalar fetch — jax.block_until_ready is a
    # no-op on some experimental platforms (observed on the axon TPU relay),
    # which silently turns a timing loop into a dispatch-latency measurement.
    for _ in range(warmup):
        m = engine.train_batch(batch)
    np.asarray(m["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        m = engine.train_batch(batch)
    np.asarray(m["loss"])
    dt = time.perf_counter() - t0

    tokens = engine.train_batch_size * seq * steps
    tok_per_sec = tokens / dt
    flops_per_token = cfg.flops_per_token(seq)
    mfu = tok_per_sec * flops_per_token / peak_flops

    result = {
        "metric": f"tokens_per_sec_per_chip_gpt2_125m_bf16_seq{seq}" if on_tpu
        else f"tokens_per_sec_cpu_smoke_seq{seq}",
        "value": round(tok_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.45, 4),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
