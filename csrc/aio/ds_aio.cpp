// Async file I/O thread pool for deepspeed_tpu.
//
// TPU-native analog of the reference DeepNVMe/AIO native layer
// (csrc/aio/common/* + py_lib/py_ds_aio.cpp: aio_read/aio_write handles with
// a pthread worker pool over pread/pwrite). Rationale is identical: Python
// threads serialize on the GIL and synchronous IO stalls the training loop;
// a C++ pool drives NVMe queues from outside the interpreter while JAX's
// async dispatch keeps the TPU busy. Plain pread/pwrite on worker threads
// (the reference's aio_handle also supports this mode); io_uring/libaio can
// slot behind the same interface later.
//
// C ABI (ctypes-friendly, no pybind11 in this image):
//   pool  = ds_aio_pool_create(num_threads)
//   req   = ds_aio_submit(pool, path, buf, nbytes, file_offset, is_write)
//   ok    = ds_aio_wait(pool, req)        // 0 on success, -errno on failure
//   n     = ds_aio_wait_all(pool)         // number of failed requests
//           ds_aio_pool_destroy(pool)

#include <atomic>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>
#include <unistd.h>
#include <sys/stat.h>
#include <sys/types.h>

namespace {

struct Request {
  std::string path;
  char* buf = nullptr;
  long nbytes = 0;
  long offset = 0;
  bool is_write = false;
  bool claimed = false;        // guarded by Pool::mu — one waiter owns a request
  std::atomic<int> status{1};  // 1 = pending, 0 = ok, <0 = -errno
};

struct Pool {
  std::vector<std::thread> workers;
  std::deque<long> queue;
  std::unordered_map<long, Request*> requests;
  std::mutex mu;
  std::condition_variable cv_submit;   // workers wait for work
  std::condition_variable cv_done;     // waiters wait for completions
  long next_id = 1;
  bool stopping = false;

  explicit Pool(int num_threads) {
    for (int i = 0; i < num_threads; ++i) {
      workers.emplace_back([this] { run(); });
    }
  }

  ~Pool() {
    {
      std::lock_guard<std::mutex> g(mu);
      stopping = true;
    }
    cv_submit.notify_all();
    for (auto& t : workers) t.join();
    for (auto& kv : requests) delete kv.second;
  }

  static int do_io(Request* r) {
    const int flags = r->is_write ? (O_WRONLY | O_CREAT) : O_RDONLY;
    int fd = ::open(r->path.c_str(), flags, 0644);
    if (fd < 0) return -errno;
    long done = 0;
    int rc = 0;
    while (done < r->nbytes) {
      ssize_t n = r->is_write
                      ? ::pwrite(fd, r->buf + done, r->nbytes - done, r->offset + done)
                      : ::pread(fd, r->buf + done, r->nbytes - done, r->offset + done);
      if (n < 0) {
        if (errno == EINTR) continue;
        rc = -errno;
        break;
      }
      if (n == 0) {  // short read: file smaller than requested
        rc = -1;
        break;
      }
      done += n;
    }
    ::close(fd);
    return rc;
  }

  void run() {
    for (;;) {
      Request* r = nullptr;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_submit.wait(lk, [this] { return stopping || !queue.empty(); });
        if (stopping && queue.empty()) return;
        long id = queue.front();
        queue.pop_front();
        r = requests[id];
      }
      int rc = do_io(r);
      {
        // store + notify under the mutex: a waiter that checked the predicate
        // and is about to block must not miss this wakeup
        std::lock_guard<std::mutex> g(mu);
        r->status.store(rc);
      }
      cv_done.notify_all();
    }
  }

  long submit(const char* path, void* buf, long nbytes, long offset, int is_write) {
    auto* r = new Request();
    r->path = path;
    r->buf = static_cast<char*>(buf);
    r->nbytes = nbytes;
    r->offset = offset;
    r->is_write = is_write != 0;
    long id;
    {
      std::lock_guard<std::mutex> g(mu);
      id = next_id++;
      requests[id] = r;
      queue.push_back(id);
    }
    cv_submit.notify_one();
    return id;
  }

  int wait(long id) {
    Request* r;
    {
      std::lock_guard<std::mutex> g(mu);
      auto it = requests.find(id);
      if (it == requests.end()) return -2;  // unknown id (double wait)
      r = it->second;
      if (r->claimed) return -2;  // another waiter owns it (concurrent wait)
      r->claimed = true;
    }
    {
      std::unique_lock<std::mutex> lk(mu);
      cv_done.wait(lk, [r] { return r->status.load() != 1; });
    }
    int rc = r->status.load();
    {
      std::lock_guard<std::mutex> g(mu);
      requests.erase(id);
    }
    delete r;
    return rc;
  }

  int wait_all() {
    std::vector<long> ids;
    {
      std::lock_guard<std::mutex> g(mu);
      ids.reserve(requests.size());
      for (auto& kv : requests) ids.push_back(kv.first);
    }
    int failures = 0;
    for (long id : ids) {
      int rc = wait(id);
      if (rc != 0 && rc != -2) ++failures;  // -2: claimed by a concurrent waiter
    }
    return failures;
  }
};

}  // namespace

extern "C" {

void* ds_aio_pool_create(int num_threads) {
  return new Pool(num_threads > 0 ? num_threads : 4);
}

void ds_aio_pool_destroy(void* pool) { delete static_cast<Pool*>(pool); }

long ds_aio_submit(void* pool, const char* path, void* buf, long nbytes,
                   long offset, int is_write) {
  return static_cast<Pool*>(pool)->submit(path, buf, nbytes, offset, is_write);
}

int ds_aio_wait(void* pool, long req_id) {
  return static_cast<Pool*>(pool)->wait(req_id);
}

int ds_aio_wait_all(void* pool) { return static_cast<Pool*>(pool)->wait_all(); }

}  // extern "C"
